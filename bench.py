"""Headline benchmark: GPT-2 124M training throughput on the real TPU,
measured THROUGH the product path: JaxTrainer → BackendExecutor → a
TPU-claiming worker actor running the train loop (the Ray-Train-style
GPT-2 of BASELINE.json; reference analog:
release/air_tests/air_benchmarks/workloads/torch_benchmark.py:214-222).

Prints ONE JSON line:
  {"metric": "gpt2_124m_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": MFU/0.45, ...}

vs_baseline is measured MFU against the north-star 45% MFU target from
BASELINE.json (the reference repo publishes no absolute numbers —
BASELINE.md).

The driver pins its own jax to CPU (never claiming the tunneled chip) and
leaves the claim env intact for the spawned TPU worker, which is the sole
TPU claimant.  BENCH_PATH=raw runs the step loop directly in this process
instead (no cluster) for path-overhead comparison.
"""

from __future__ import annotations

import json
import os
import sys
import time

# bf16 peak FLOP/s per chip by generation
_PEAK = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _tpu_available() -> bool:
    """Probe ``jax.devices()`` in a THROWAWAY subprocess.  A failed
    TPU/axon backend init poisons the jax runtime of the process that
    attempted it (and the driver must never claim the tunneled chip
    itself), so the probe gets its own interpreter with the same env the
    TPU worker would inherit.  rc!=0 → no usable accelerator backend."""
    import subprocess

    try:
        return (
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True,
                timeout=180,
            ).returncode
            == 0
        )
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bench_config():
    return {
        "model": os.environ.get("BENCH_MODEL", "gpt2_124m"),
        # batch 18 is the sweet spot on a 16G v5e: largest batch whose
        # [B,S,V] f32 logits still fit the naive-CE budget (no backward
        # recompute); 30 steps measures steady state past warmup jitter
        "batch": int(os.environ.get("BENCH_BATCH", "18")),
        "steps": int(os.environ.get("BENCH_STEPS", "30")),
        "remat": os.environ.get("BENCH_REMAT", ""),
        "attn": os.environ.get("BENCH_ATTN", ""),
        "scores": os.environ.get("BENCH_SCORES", "bf16"),
        "ce_chunk": os.environ.get("BENCH_CE_CHUNK", ""),
    }


def _build_bundle(cfg_d):
    """Model + jitted train step on THIS process's devices (runs inside the
    TPU worker on the train path; in-process on the raw path)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import GPT2Config, GPT2Model
    from ray_tpu.models.lm_train import make_train_step, synthetic_batch
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg_kw = {}
    if cfg_d["remat"]:
        cfg_kw["remat_policy"] = cfg_d["remat"]
        cfg_kw["remat"] = cfg_d["remat"] != "none"
    if cfg_d["attn"]:
        cfg_kw["attention_impl"] = cfg_d["attn"]
    if cfg_d["scores"] == "bf16":
        # bf16 attention scores halve [S,S] HBM traffic on the xla path
        cfg_kw["attn_scores_dtype"] = jnp.bfloat16
    if cfg_d["ce_chunk"]:
        cfg_kw["loss_chunk"] = int(cfg_d["ce_chunk"])
    cfg = getattr(GPT2Config, cfg_d["model"])(**cfg_kw)
    model = GPT2Model(cfg)
    devices = jax.devices()
    mesh = make_mesh(MeshConfig(dp=1), devices[:1])
    bundle = make_train_step(model, mesh, learning_rate=3e-4)
    return cfg, bundle, devices


def _run_steps(cfg_d):
    """The measured loop; returns a metrics dict.  Called inside whichever
    process owns the chip."""
    import jax

    from ray_tpu.models.lm_train import synthetic_batch

    cfg, bundle, devices = _build_bundle(cfg_d)
    batch, steps = cfg_d["batch"], cfg_d["steps"]
    seq = cfg.block_size

    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    tokens, targets = synthetic_batch(jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)
    tokens = jax.device_put(tokens, bundle.batch_sharding)
    targets = jax.device_put(targets, bundle.batch_sharding)

    # warmup (compile); a host fetch is the sync barrier — block_until_ready
    # is unreliable on the experimental axon PJRT backend
    for _ in range(2):
        params, opt_state, metrics = bundle.step(params, opt_state, tokens, targets)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, metrics = bundle.step(params, opt_state, tokens, targets)
    final_loss = float(metrics["loss"])  # forces the whole step chain
    dt = time.perf_counter() - t0

    # probed pass AFTER the timed loop: per-step breakdown + jitter via
    # the flight recorder's StepProbe (train/jax/step_probe.py) without
    # perturbing the headline async-dispatch throughput above (the probe
    # brackets compute with a sync point by design)
    probe_steps = max(4, steps // 4)
    jitter = {}
    try:
        from ray_tpu.train.jax import StepProbe

        probe = StepProbe(
            "bench_gpt2",
            flops_per_step=cfg.flops_per_token() * batch * seq,
        )
        for _ in range(probe_steps):
            with probe.step():
                with probe.phase("compute"):
                    params, opt_state, metrics = bundle.step(
                        params, opt_state, tokens, targets
                    )
                    probe.block(metrics)
                with probe.phase("metrics_fold"):
                    float(metrics["loss"])
        probe.flush()
        st = probe.stats()
        jitter = {
            "probed_step_ms_p50": round(st.get("p50_s", 0) * 1e3, 2),
            "probed_step_ms_p99": round(st.get("p99_s", 0) * 1e3, 2),
            "step_jitter_pct": round(st.get("jitter_pct", 0), 2),
        }
    except Exception as e:  # noqa: BLE001 — the headline number stands alone
        jitter = {"probe_error": str(e)[:200]}

    return {
        "platform": devices[0].platform,
        "tokens_per_sec": batch * seq * steps / dt,
        "flops_per_token": cfg.flops_per_token(),
        "step_ms": 1000 * dt / steps,
        "seq": seq,
        "loss": final_loss,
        **jitter,
    }


def _try_steps(cfg):
    try:
        return _run_steps(cfg)
    except Exception as e:  # noqa: BLE001
        return {"error": str(e)[:200]}


_BACKEND_ERR_MARKERS = (
    "UNAVAILABLE",
    "Unable to initialize backend",
    "TPU backend setup",
)


def _is_backend_error(e: BaseException) -> bool:
    msg = f"{type(e).__name__}: {e}"
    return any(m in msg for m in _BACKEND_ERR_MARKERS)


def _train_loop(config):
    """Runs on the TPU worker actor via JaxTrainer.  config carries the
    primary model config and optionally a "secondary" config benched in
    the same worker process (the chip has one claimant per session).

    The driver's TPU probe can pass while the WORKER's backend init still
    fails (flaky tunnel — BENCH_r05 died rc=1 exactly here): report the
    failure as data instead of raising, so the driver can fall back to
    CPU and say so in the JSON."""
    from ray_tpu.air import session

    secondary = config.pop("secondary", None)
    try:
        out = _run_steps(config)
    except Exception as e:  # noqa: BLE001
        if _is_backend_error(e):
            session.report({"backend_error": f"{type(e).__name__}: {e}"[:500]})
            return
        raise
    if secondary is not None and out["platform"] not in ("cpu",):
        out["secondary"] = _try_steps(secondary)
    session.report(out)


def _dispatch_pair():
    """Per-step driver-overhead pair (ROADMAP item 2): the SAME tiny LM
    ``TrainStepSpec`` driven through the eager per-step actor-call path vs
    the gang-armed resident DAG loop (train/jax/step_dag.py), through the
    real cluster.  Identical stage functions, identical model/config — the
    per-step wall-clock gap is the driver dispatch cost the resident DAG
    deletes.  Runs LAST (the headline fit has released the chip) and pins
    the pair to CPU: dispatch is a host-path property, and the pair must
    never re-claim the chip."""
    import ray_tpu
    from ray_tpu.models.lm_train import make_lm_step_spec
    from ray_tpu.train._internal.worker_group import TrainWorker
    from ray_tpu.train.jax.step_dag import TrainStepDag, _EagerSpecDriver

    os.environ["JAX_PLATFORMS"] = "cpu"
    steps = int(os.environ.get("BENCH_DISPATCH_STEPS", "60"))
    ray_tpu.init(num_cpus=4)
    try:
        spec = make_lm_step_spec(
            "tiny",
            batch=2,
            seq=64,
            steps=1 << 30,  # driven by the timers below, not the spec
            sync_grads=False,
            name="bench_dispatch",
        )
        tw = ray_tpu.remote(TrainWorker).remote(0, 1)
        eager = _EagerSpecDriver([tw], spec, None, 0)
        eager.run(5)  # build + jit warmup off the clock
        t0 = time.perf_counter()
        eager.run(steps)
        eager_ms = (time.perf_counter() - t0) / steps * 1e3
        eager.finish()
        dag = TrainStepDag([tw], spec)  # rebuilds state; same seed
        dag.run(5)
        t0 = time.perf_counter()
        dag.run(steps)
        dag_ms = (time.perf_counter() - t0) / steps * 1e3
        dag.teardown()
        return {
            "eager_step_ms": round(eager_ms, 3),
            "dag_step_ms": round(dag_ms, 3),
            "driver_overhead_ms": round(eager_ms - dag_ms, 3),
            "dispatch_speedup": round(eager_ms / dag_ms, 2),
            "model": "tiny",
            "steps": steps,
        }
    finally:
        ray_tpu.shutdown()


def main():
    cfg_d = _bench_config()
    raw = os.environ.get("BENCH_PATH", "train") == "raw"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK.get(gen, _PEAK["v5e"])

    # No usable TPU backend → run the whole path on CPU and SAY SO in the
    # JSON instead of dying with a raw JaxRuntimeError (the env stays
    # changed before any in-process jax import, so the spawned worker
    # inherits the fallback too).
    cpu_fallback = False
    if not _tpu_available():
        os.environ["JAX_PLATFORMS"] = "cpu"
        cpu_fallback = True

    cfg2 = None
    if os.environ.get("BENCH_SECONDARY", "1") != "0":
        # secondary row: gpt2_350m on the same chip (BASELINE config #4
        # evidence ladder — the 1.5B shape itself is validated by the
        # dryrun's ZeRO-1 shard assertions)
        cfg2 = dict(cfg_d)
        cfg2["model"] = "gpt2_350m"
        cfg2["batch"] = int(os.environ.get("BENCH_BATCH_350M", "8"))
        cfg2["steps"] = 10

    m2 = None
    backend_note = ""
    if raw:
        m = _run_steps(cfg_d)
        if cfg2 is not None and m["platform"] not in ("cpu",):
            m2 = _try_steps(cfg2)
    else:
        # the driver must never claim the tunneled chip: pin its jax to CPU
        # (claim env stays in os.environ so the spawned TPU worker inherits it)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import ray_tpu
        from ray_tpu.train import JaxTrainer, ScalingConfig

        def _fit(use_tpu: bool):
            # use_tpu=False runs the same train path on a pool worker
            # (spawned with JAX_PLATFORMS=cpu — it can never touch the
            # claim env), which is what the CPU fallback needs
            ray_tpu.init(num_cpus=4, num_tpus=1 if use_tpu else 0)
            try:
                trainer = JaxTrainer(
                    _train_loop,
                    train_loop_config={**cfg_d, "secondary": cfg2},
                    scaling_config=ScalingConfig(num_workers=1, use_tpu=use_tpu),
                )
                return trainer.fit().metrics
            finally:
                ray_tpu.shutdown()

        m = None
        if not cpu_fallback:
            try:
                m = _fit(use_tpu=True)
            except Exception as e:  # noqa: BLE001
                # a sideways TPU backend can also KILL the worker outright
                # (libtpu init abort) instead of raising in user code —
                # same fallback, the crash is the evidence
                backend_note = f"{type(e).__name__}: {e}"[:500]
        if m is None or m.get("backend_error"):
            # the probe said TPU but the spawned train worker's backend
            # failed anyway (BENCH_r05 died rc=1 exactly here): fall back
            # to CPU THROUGH the same train path and carry the evidence
            # in the JSON instead of dying
            if m is not None:
                backend_note = m["backend_error"]
            cpu_fallback = True
            os.environ["JAX_PLATFORMS"] = "cpu"
            m = _fit(use_tpu=False)
        m2 = m.pop("secondary", None)

    on_tpu = m["platform"] not in ("cpu",)
    mfu = m["tokens_per_sec"] * m["flops_per_token"] / peak
    result = {
        "metric": "gpt2_124m_tokens_per_sec_per_chip",
        "value": round(m["tokens_per_sec"], 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "platform": m["platform"],
        "backend": "cpu_fallback" if cpu_fallback else m["platform"],
        **({"backend_note": backend_note} if backend_note else {}),
        "tpu_gen": gen if on_tpu else "cpu-fallback",
        "path": "raw" if raw else "train",
        "batch": cfg_d["batch"],
        "seq": m["seq"],
        "step_ms": round(m["step_ms"], 2),
        "loss": round(m["loss"], 4),
    }

    # step-dispatch pair: eager JaxTrainer loop vs the DAG-resident loop
    # on the same model/config — the tracked driver-overhead line
    # (scripts/perf_trends.py series bench.train_dispatch_*)
    if not raw and os.environ.get("BENCH_DISPATCH", "1") != "0":
        try:
            result["step_dispatch"] = _dispatch_pair()
        except Exception as e:  # noqa: BLE001 — the headline number stands alone
            result["step_dispatch"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    if m2 is not None:
        if "error" in m2:
            result["gpt2_350m"] = m2
        else:
            mfu2 = m2["tokens_per_sec"] * m2["flops_per_token"] / peak
            result["gpt2_350m"] = {
                "tokens_per_sec_per_chip": round(m2["tokens_per_sec"], 1),
                "mfu": round(mfu2, 4),
                "batch": cfg2["batch"],
                "step_ms": round(m2["step_ms"], 2),
                "loss": round(m2["loss"], 4),
            }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
