"""Headline benchmark: GPT-2 124M training throughput on the real TPU.

Prints ONE JSON line:
  {"metric": "gpt2_124m_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": MFU/0.45, ...}

vs_baseline is measured MFU against the north-star 45% MFU target from
BASELINE.json (reference repo publishes no absolute numbers — BASELINE.md).

Run with the ambient env (sole TPU claimant).  Everything else in this repo
runs on cpu; only this script touches the chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

# bf16 peak FLOP/s per chip by generation
_PEAK = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def main():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import GPT2Config, GPT2Model
    from ray_tpu.models.lm_train import make_train_step, synthetic_batch
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh

    devices = jax.devices()
    platform = devices[0].platform
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK.get(gen, _PEAK["v5e"])
    on_tpu = platform not in ("cpu",)

    model_name = os.environ.get("BENCH_MODEL", "gpt2_124m")
    cfg_kw = {}
    if os.environ.get("BENCH_REMAT"):
        cfg_kw["remat_policy"] = os.environ["BENCH_REMAT"]
        cfg_kw["remat"] = os.environ["BENCH_REMAT"] != "none"
    if os.environ.get("BENCH_ATTN"):
        cfg_kw["attention_impl"] = os.environ["BENCH_ATTN"]
    # bf16 attention scores halve the [S,S] HBM traffic (+17% throughput
    # measured on v5e); softmax still accumulates f32.  BENCH_SCORES=f32
    # reverts to the conservative default.
    if os.environ.get("BENCH_SCORES", "bf16") == "bf16":
        import jax.numpy as _jnp

        cfg_kw["attn_scores_dtype"] = _jnp.bfloat16
    cfg = getattr(GPT2Config, model_name)(**cfg_kw)
    model = GPT2Model(cfg)
    mesh = make_mesh(MeshConfig(dp=1), devices[:1])

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    seq = cfg.block_size
    steps = int(os.environ.get("BENCH_STEPS", "10"))

    bundle = make_train_step(model, mesh, learning_rate=3e-4)
    params, opt_state = bundle.init(jax.random.PRNGKey(0))
    tokens, targets = synthetic_batch(jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)
    tokens = jax.device_put(tokens, bundle.batch_sharding)
    targets = jax.device_put(targets, bundle.batch_sharding)

    # warmup (compile); a host fetch is the sync barrier — block_until_ready
    # is unreliable on the experimental axon PJRT backend
    for _ in range(2):
        params, opt_state, metrics = bundle.step(params, opt_state, tokens, targets)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, metrics = bundle.step(params, opt_state, tokens, targets)
    final_loss = float(metrics["loss"])  # forces the whole step chain
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    mfu = tokens_per_sec * cfg.flops_per_token() / peak
    result = {
        "metric": "gpt2_124m_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "platform": platform,
        "tpu_gen": gen if on_tpu else "cpu-fallback",
        "batch": batch,
        "seq": seq,
        "step_ms": round(1000 * dt / steps, 2),
        "loss": round(final_loss, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
