// Native unit tests for the shm object store (reference analog:
// src/ray/object_manager/plasma/ test suite run under the sanitizer
// configs in .bazelrc:92-102).  Built and run by tests/test_native.py
// under -fsanitize=address and -fsanitize=thread.
//
// Includes store.cc directly (single-TU) so the robust-mutex crash test
// can reach the segment header.

#include "store.cc"

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>
#include <sys/wait.h>

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, __LINE__,      \
              #cond);                                                      \
      abort();                                                             \
    }                                                                      \
  } while (0)

namespace {

void make_id(uint8_t* id, uint64_t n) {
  memset(id, 0, kIdLen);
  memcpy(id, &n, sizeof(n));
}

std::string tmp_path(const char* name) {
  const char* base = getenv("STORE_TEST_DIR");
  std::string p = base ? base : "/dev/shm";
  p += "/";
  p += name;
  return p;
}

void test_lifecycle() {
  std::string path = tmp_path("store_test_basic");
  void* s = store_create(path.c_str(), 1 << 20, 256);
  CHECK(s != nullptr);
  uint8_t id[kIdLen];
  make_id(id, 1);
  uint64_t off = 0, size = 0;
  CHECK(store_alloc(s, id, 1000, &off) == 0);
  memset(store_base(s) + off, 0xAB, 1000);
  CHECK(store_contains(s, id) == 0);  // not sealed yet
  CHECK(store_get(s, id, &off, &size) == -3);
  CHECK(store_seal(s, id) == 0);
  CHECK(store_contains(s, id) == 1);
  CHECK(store_get(s, id, &off, &size) == 0);
  CHECK(size == 1000);
  CHECK(store_base(s)[off] == 0xAB);
  CHECK(store_release(s, id) == 0);   // reader pin
  CHECK(store_release(s, id) == 0);   // creator pin
  CHECK(store_num_objects(s) == 1);
  CHECK(store_delete(s, id) == 0);
  CHECK(store_num_objects(s) == 0);
  CHECK(store_used(s) == 0);
  CHECK(store_get(s, id, &off, &size) == -1);
  store_detach(s);
  unlink(path.c_str());
  fprintf(stderr, "test_lifecycle OK\n");
}

void test_errors() {
  std::string path = tmp_path("store_test_err");
  void* s = store_create(path.c_str(), 1 << 20, 64);
  uint8_t id[kIdLen];
  make_id(id, 7);
  uint64_t off = 0;
  CHECK(store_alloc(s, id, 100, &off) == 0);
  CHECK(store_alloc(s, id, 100, &off) == -1);  // duplicate
  CHECK(store_seal(s, id) == 0);
  CHECK(store_seal(s, id) == -1);  // double seal
  uint8_t missing[kIdLen];
  make_id(missing, 999);
  uint64_t sz;
  CHECK(store_get(s, missing, &off, &sz) == -1);
  uint8_t big[kIdLen];
  make_id(big, 8);
  CHECK(store_alloc(s, big, (1 << 20) + 1, &off) == -2);  // over capacity
  store_detach(s);
  unlink(path.c_str());
  fprintf(stderr, "test_errors OK\n");
}

void test_lru_eviction() {
  std::string path = tmp_path("store_test_lru");
  // capacity for ~4 aligned 1000-byte objects
  void* s = store_create(path.c_str(), 4 * 1024, 64);
  uint8_t id[kIdLen];
  uint64_t off;
  for (uint64_t i = 0; i < 4; i++) {
    make_id(id, i);
    CHECK(store_alloc(s, id, 1000, &off) == 0);
    CHECK(store_seal(s, id) == 0);
    CHECK(store_release(s, id) == 0);  // unpinned: evictable
  }
  // touch object 0 so object 1 is the LRU victim
  uint64_t sz;
  make_id(id, 0);
  CHECK(store_get(s, id, &off, &sz) == 0);
  CHECK(store_release(s, id) == 0);
  make_id(id, 100);
  CHECK(store_alloc(s, id, 1000, &off) == 0);  // forces one eviction
  CHECK(store_evictions(s) >= 1);
  make_id(id, 1);
  CHECK(store_contains(s, id) == 0);  // LRU victim gone
  make_id(id, 0);
  CHECK(store_contains(s, id) == 1);  // recently-touched survived
  // pinned objects are never evicted: pin everything, then alloc too much
  store_detach(s);
  unlink(path.c_str());
  fprintf(stderr, "test_lru_eviction OK\n");
}

void test_no_evict_mode_and_pins() {
  std::string path = tmp_path("store_test_noevict");
  void* s = store_create(path.c_str(), 2 * 1024, 64);
  uint8_t a[kIdLen], b[kIdLen];
  make_id(a, 1);
  make_id(b, 2);
  uint64_t off;
  CHECK(store_alloc(s, a, 900, &off) == 0);
  CHECK(store_seal(s, a) == 0);
  CHECK(store_release(s, a) == 0);
  // allow_evict=0 must refuse rather than evict the sealed object
  CHECK(store_alloc_opts(s, b, 2000, 0, &off) == -2);
  CHECK(store_contains(s, a) == 1);
  // pinned object blocks eviction even in evicting mode
  uint64_t sz;
  CHECK(store_get(s, a, &off, &sz) == 0);  // pin
  CHECK(store_alloc(s, b, 2000, &off) == -2);
  CHECK(store_contains(s, a) == 1);
  CHECK(store_release(s, a) == 0);
  CHECK(store_alloc(s, b, 2000, &off) == 0);  // now evictable
  CHECK(store_contains(s, a) == 0);
  store_detach(s);
  unlink(path.c_str());
  fprintf(stderr, "test_no_evict_mode_and_pins OK\n");
}

void test_free_coalescing() {
  std::string path = tmp_path("store_test_coalesce");
  void* s = store_create(path.c_str(), 4 * 1024, 64);
  uint8_t ids[4][kIdLen];
  uint64_t off;
  for (uint64_t i = 0; i < 4; i++) {
    make_id(ids[i], i);
    CHECK(store_alloc(s, ids[i], 1000, &off) == 0);
    CHECK(store_seal(s, ids[i]) == 0);
  }
  // delete all four non-adjacently, then allocate one object needing the
  // WHOLE region — only possible if neighbors coalesced back into one run
  CHECK(store_delete(s, ids[1]) == 0);
  CHECK(store_delete(s, ids[3]) == 0);
  CHECK(store_delete(s, ids[0]) == 0);
  CHECK(store_delete(s, ids[2]) == 0);
  CHECK(store_used(s) == 0);
  uint8_t big[kIdLen];
  make_id(big, 50);
  CHECK(store_alloc_opts(s, big, 4 * 1024, 0, &off) == 0);
  store_detach(s);
  unlink(path.c_str());
  fprintf(stderr, "test_free_coalescing OK\n");
}

void test_concurrent_churn() {
  std::string path = tmp_path("store_test_conc");
  void* s = store_create(path.c_str(), 1 << 22, 4096);
  constexpr int kThreads = 4;
  constexpr uint64_t kIters = 1500;
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; t++) {
    threads.emplace_back([s, t, &failures]() {
      // every thread attaches its own handle, like a separate worker
      uint8_t id[kIdLen];
      for (uint64_t i = 0; i < kIters; i++) {
        make_id(id, (uint64_t)t << 32 | i);
        uint64_t off, sz;
        if (store_alloc(s, id, 64 + (i % 512), &off) != 0) {
          failures++;
          continue;
        }
        if (store_seal(s, id) != 0) failures++;
        if (store_get(s, id, &off, &sz) != 0) failures++;
        if (store_release(s, id) != 0) failures++;  // reader pin
        if (store_release(s, id) != 0) failures++;  // creator pin
        if (i % 3 == 0 && store_delete_if_unpinned(s, id) != 0) failures++;
      }
    });
  }
  // a churn observer scanning candidates concurrently
  std::thread scanner([s]() {
    std::vector<uint8_t> ids(64 * kIdLen);
    std::vector<uint64_t> sizes(64);
    for (int i = 0; i < 200; i++) {
      store_evict_candidates(s, 64, ids.data(), sizes.data());
    }
  });
  for (auto& th : threads) th.join();
  scanner.join();
  CHECK(failures.load() == 0);
  store_detach(s);
  unlink(path.c_str());
  fprintf(stderr, "test_concurrent_churn OK\n");
}

void test_robust_mutex_crash_unlock() {
  std::string path = tmp_path("store_test_robust");
  void* s = store_create(path.c_str(), 1 << 20, 64);
  uint8_t id[kIdLen];
  make_id(id, 3);
  uint64_t off;
  CHECK(store_alloc(s, id, 128, &off) == 0);
  CHECK(store_seal(s, id) == 0);
  pid_t pid = fork();
  CHECK(pid >= 0);
  if (pid == 0) {
    // child: attach, take the segment lock, die holding it (simulated
    // worker crash mid-operation)
    void* c = store_attach(path.c_str());
    if (!c) _exit(2);
    Store* cs = (Store*)c;
    pthread_mutex_lock(&cs->hdr->mutex);
    _exit(0);  // no unlock: robust mutex must recover
  }
  int status = 0;
  CHECK(waitpid(pid, &status, 0) == pid);
  CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  // every subsequent operation must recover via EOWNERDEAD + consistent
  uint64_t sz;
  CHECK(store_get(s, id, &off, &sz) == 0);
  CHECK(store_release(s, id) == 0);
  uint8_t id2[kIdLen];
  make_id(id2, 4);
  CHECK(store_alloc(s, id2, 64, &off) == 0);
  CHECK(store_seal(s, id2) == 0);
  store_detach(s);
  unlink(path.c_str());
  fprintf(stderr, "test_robust_mutex_crash_unlock OK\n");
}

}  // namespace

int main() {
  test_lifecycle();
  test_errors();
  test_lru_eviction();
  test_no_evict_mode_and_pins();
  test_free_coalescing();
  test_concurrent_churn();
#ifndef STORE_TEST_NO_FORK
  // TSan forbids fork-with-threads; the churn test above already ran
  // threads, so skip the fork-based robust-mutex test under TSan.
  test_robust_mutex_crash_unlock();
#endif
  fprintf(stderr, "store_test: ALL OK\n");
  return 0;
}
