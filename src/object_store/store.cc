// Shared-memory object store: the node-local zero-copy data plane.
//
// TPU-native equivalent of the reference's Plasma store (reference:
// src/ray/object_manager/plasma/{store.cc,object_lifecycle_manager.h,
// plasma_allocator.cc,dlmalloc.cc,eviction_policy.h}).  Differences by
// design: instead of a store *server* process with a unix-socket protocol
// and fd-passing (plasma/fling.cc), every client maps one shared segment
// and operates on it directly under a process-shared robust mutex — on a
// TPU-VM host all workers are local, so the socket hop is pure overhead.
// Create/seal/get/release/delete + LRU eviction of unpinned sealed objects
// match plasma semantics; sealed buffers are immutable and consumable
// zero-copy (numpy/jax via dlpack from the mapped pages).
//
// Layout of the segment:
//   [Header][Slot * nslots][FreeBlock * MAX_FREE][data region ...]
//
// Build: g++ -O2 -shared -fPIC -o libray_tpu_store.so store.cc -lpthread

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <vector>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7470755f73746f72ULL;  // "tpu_stor"
constexpr uint32_t kIdLen = 28;
constexpr uint64_t kMaxFree = 1 << 14;
constexpr uint64_t kAlign = 64;

enum SlotState : uint32_t {
  EMPTY = 0,
  ALLOCATED = 1,  // created, not yet sealed
  SEALED = 2,
  TOMBSTONE = 3,
};

struct Slot {
  uint8_t id[kIdLen];
  uint32_t state;
  int32_t refcount;  // pins from get(); evictable only at 0
  uint64_t offset;   // into data region
  uint64_t size;     // total payload bytes
  uint64_t lru_tick;
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;   // data region bytes
  uint64_t nslots;
  uint64_t used;       // allocated bytes
  uint64_t lru_clock;
  uint64_t nfree;      // entries in free list
  uint64_t num_objects;
  uint64_t evictions;
  pthread_mutex_t mutex;
};

struct Store {
  Header* hdr;
  Slot* slots;
  FreeBlock* freelist;
  uint8_t* data;
  void* base;
  uint64_t mapped_size;
};

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 28-byte id
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

class Locker {
 public:
  explicit Locker(Header* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->mutex);
    if (rc == EOWNERDEAD) {
      // A worker died holding the lock; state is still consistent because
      // all mutations are single-field or ordered (same recovery stance as
      // plasma's store-restart).  Mark consistent and continue.
      pthread_mutex_consistent(&h_->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->mutex); }

 private:
  Header* h_;
};

// Find slot for id, or an insertion slot if insert==true.
Slot* find_slot(Store* s, const uint8_t* id, bool insert) {
  uint64_t n = s->hdr->nslots;
  uint64_t i = hash_id(id) % n;
  Slot* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < n; probe++, i = (i + 1) % n) {
    Slot* sl = &s->slots[i];
    if (sl->state == EMPTY) {
      if (insert) return first_tomb ? first_tomb : sl;
      return nullptr;
    }
    if (sl->state == TOMBSTONE) {
      if (insert && !first_tomb) first_tomb = sl;
      continue;
    }
    if (memcmp(sl->id, id, kIdLen) == 0) return sl;
  }
  return insert ? first_tomb : nullptr;
}

// First-fit allocate from the free list; returns UINT64_MAX on failure.
uint64_t alloc_block(Store* s, uint64_t size) {
  Header* h = s->hdr;
  for (uint64_t i = 0; i < h->nfree; i++) {
    FreeBlock* fb = &s->freelist[i];
    if (fb->size >= size) {
      uint64_t off = fb->offset;
      fb->offset += size;
      fb->size -= size;
      if (fb->size == 0) {
        s->freelist[i] = s->freelist[h->nfree - 1];
        h->nfree--;
      }
      h->used += size;
      return off;
    }
  }
  return UINT64_MAX;
}

void free_block(Store* s, uint64_t offset, uint64_t size) {
  Header* h = s->hdr;
  h->used -= size;  // account before coalescing grows `size` with already-free bytes
  // insert and coalesce with neighbors
  uint64_t end = offset + size;
  for (uint64_t i = 0; i < h->nfree;) {
    FreeBlock* fb = &s->freelist[i];
    if (fb->offset + fb->size == offset) {  // fb | block
      offset = fb->offset;
      size += fb->size;
      end = offset + size;
      s->freelist[i] = s->freelist[h->nfree - 1];
      h->nfree--;
      continue;
    }
    if (end == fb->offset) {  // block | fb
      size += fb->size;
      s->freelist[i] = s->freelist[h->nfree - 1];
      h->nfree--;
      continue;
    }
    i++;
  }
  if (h->nfree < kMaxFree) {
    s->freelist[h->nfree++] = FreeBlock{offset, size};
  }
  // else: leak the block (bounded by kMaxFree fragmentation; extremely rare)
}

// Evict the least-recently-used sealed, unpinned object.  Returns freed bytes.
uint64_t evict_one(Store* s) {
  Header* h = s->hdr;
  Slot* victim = nullptr;
  for (uint64_t i = 0; i < h->nslots; i++) {
    Slot* sl = &s->slots[i];
    if (sl->state == SEALED && sl->refcount == 0) {
      if (!victim || sl->lru_tick < victim->lru_tick) victim = sl;
    }
  }
  if (!victim) return 0;
  uint64_t sz = victim->size;
  free_block(s, victim->offset, align_up(victim->size));
  victim->state = TOMBSTONE;
  h->num_objects--;
  h->evictions++;
  return sz;
}

}  // namespace

extern "C" {

// Create a fresh store segment at `path` (tmpfs file, e.g. /dev/shm/...).
void* store_create(const char* path, uint64_t capacity, uint64_t nslots) {
  uint64_t meta = sizeof(Header) + nslots * sizeof(Slot) + kMaxFree * sizeof(FreeBlock);
  meta = align_up(meta);
  uint64_t total = meta + capacity;
  int fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Store* s = new Store;
  s->base = base;
  s->mapped_size = total;
  s->hdr = (Header*)base;
  s->slots = (Slot*)((uint8_t*)base + sizeof(Header));
  s->freelist = (FreeBlock*)((uint8_t*)base + sizeof(Header) + nslots * sizeof(Slot));
  s->data = (uint8_t*)base + meta;
  Header* h = s->hdr;
  memset(h, 0, sizeof(Header));
  memset(s->slots, 0, nslots * sizeof(Slot));
  h->capacity = capacity;
  h->nslots = nslots;
  h->nfree = 1;
  s->freelist[0] = FreeBlock{0, capacity};
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  h->magic = kMagic;
  return s;
}

// Attach to an existing segment.
void* store_attach(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* h = (Header*)base;
  if (h->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    return nullptr;
  }
  Store* s = new Store;
  s->base = base;
  s->mapped_size = (size_t)st.st_size;
  s->hdr = h;
  s->slots = (Slot*)((uint8_t*)base + sizeof(Header));
  s->freelist = (FreeBlock*)((uint8_t*)base + sizeof(Header) + h->nslots * sizeof(Slot));
  uint64_t meta = sizeof(Header) + h->nslots * sizeof(Slot) + kMaxFree * sizeof(FreeBlock);
  s->data = (uint8_t*)base + align_up(meta);
  return s;
}

void store_detach(void* sp) {
  Store* s = (Store*)sp;
  munmap(s->base, s->mapped_size);
  delete s;
}

// Allocate an object; returns 0 ok (offset from segment base in *out_offset),
// -1 already exists, -2 out of memory, -3 table full.
// allow_evict=0 never drops other objects to make room (-2 instead): the
// spilling path uses this so in-scope data is spilled to disk by policy
// rather than silently deleted by LRU (reference analog: spilling runs
// BEFORE eviction of referenced objects, raylet/local_object_manager.h).
int store_alloc_opts(void* sp, const uint8_t* id, uint64_t size, int allow_evict,
                     uint64_t* out_offset) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* existing = find_slot(s, id, false);
  if (existing && existing->state != TOMBSTONE) return -1;
  uint64_t need = align_up(size);
  if (need > s->hdr->capacity) return -2;
  uint64_t off = alloc_block(s, need);
  while (off == UINT64_MAX) {
    if (!allow_evict) return -2;
    if (evict_one(s) == 0) return -2;
    off = alloc_block(s, need);
  }
  Slot* sl = find_slot(s, id, true);
  if (!sl) {
    free_block(s, off, need);
    return -3;
  }
  memcpy(sl->id, id, kIdLen);
  sl->state = ALLOCATED;
  sl->refcount = 1;  // creator holds a pin until seal+release
  sl->offset = off;
  sl->size = size;
  sl->lru_tick = ++s->hdr->lru_clock;
  s->hdr->num_objects++;
  *out_offset = (uint64_t)(s->data - (uint8_t*)s->base) + off;
  return 0;
}

int store_alloc(void* sp, const uint8_t* id, uint64_t size, uint64_t* out_offset) {
  return store_alloc_opts(sp, id, size, 1, out_offset);
}

// List up to max_n spill/eviction candidates (sealed, unpinned), least
// recently used first.  out_ids receives max_n*kIdLen bytes, out_sizes the
// payload sizes.  Returns the count written.
int store_evict_candidates(void* sp, uint64_t max_n, uint8_t* out_ids,
                           uint64_t* out_sizes) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  struct Cand {
    Slot* sl;
    uint64_t tick;
  };
  std::vector<Cand> cands;
  for (uint64_t i = 0; i < s->hdr->nslots; i++) {
    Slot* sl = &s->slots[i];
    if (sl->state == SEALED && sl->refcount == 0) {
      cands.push_back({sl, sl->lru_tick});
    }
  }
  std::sort(cands.begin(), cands.end(),
            [](const Cand& a, const Cand& b) { return a.tick < b.tick; });
  uint64_t n = cands.size() < max_n ? cands.size() : max_n;
  for (uint64_t i = 0; i < n; i++) {
    memcpy(out_ids + i * kIdLen, cands[i].sl->id, kIdLen);
    out_sizes[i] = cands[i].sl->size;
  }
  return (int)n;
}

int store_seal(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* sl = find_slot(s, id, false);
  if (!sl || sl->state != ALLOCATED) return -1;
  sl->state = SEALED;
  return 0;
}

// Pin + locate a sealed object. 0 ok, -1 missing, -3 not sealed yet.
int store_get(void* sp, const uint8_t* id, uint64_t* out_offset, uint64_t* out_size) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* sl = find_slot(s, id, false);
  if (!sl || sl->state == TOMBSTONE) return -1;
  if (sl->state != SEALED) return -3;
  sl->refcount++;
  sl->lru_tick = ++s->hdr->lru_clock;
  *out_offset = (uint64_t)(s->data - (uint8_t*)s->base) + sl->offset;
  *out_size = sl->size;
  return 0;
}

int store_release(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* sl = find_slot(s, id, false);
  if (!sl || sl->state == TOMBSTONE) return -1;
  if (sl->refcount > 0) sl->refcount--;
  return 0;
}

int store_contains(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* sl = find_slot(s, id, false);
  return (sl && sl->state == SEALED) ? 1 : 0;
}

// Delete regardless of pins (caller must know it is safe) — used by the
// owner-driven free path.  -1 missing.
int store_delete(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* sl = find_slot(s, id, false);
  if (!sl || sl->state == TOMBSTONE) return -1;
  free_block(s, sl->offset, align_up(sl->size));
  sl->state = TOMBSTONE;
  s->hdr->num_objects--;
  return 0;
}

// Delete only if no reader currently pins the object (spill path: a pinned
// zero-copy view must never have its backing block freed under it).
// 0 deleted, -1 missing, -2 pinned.
int store_delete_if_unpinned(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* sl = find_slot(s, id, false);
  if (!sl || sl->state == TOMBSTONE) return -1;
  if (sl->refcount > 0) return -2;
  free_block(s, sl->offset, align_up(sl->size));
  sl->state = TOMBSTONE;
  s->hdr->num_objects--;
  return 0;
}

// Abort an unsealed create (creator-side failure path).
int store_abort(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Locker lock(s->hdr);
  Slot* sl = find_slot(s, id, false);
  if (!sl || sl->state != ALLOCATED) return -1;
  free_block(s, sl->offset, align_up(sl->size));
  sl->state = TOMBSTONE;
  s->hdr->num_objects--;
  return 0;
}

uint64_t store_capacity(void* sp) { return ((Store*)sp)->hdr->capacity; }
uint64_t store_used(void* sp) { return ((Store*)sp)->hdr->used; }
uint64_t store_num_objects(void* sp) { return ((Store*)sp)->hdr->num_objects; }
uint64_t store_evictions(void* sp) { return ((Store*)sp)->hdr->evictions; }

uint8_t* store_base(void* sp) { return (uint8_t*)((Store*)sp)->base; }
uint64_t store_mapped_size(void* sp) { return ((Store*)sp)->mapped_size; }

}  // extern "C"
