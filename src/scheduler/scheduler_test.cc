// Native unit tests for the fixed-point scheduler core (reference analog:
// scheduling-policy unit tests run under the sanitizer configs in
// .bazelrc:92-102).  Built and run by tests/test_native.py under
// -fsanitize=address and -fsanitize=thread.

#include "scheduler.cc"

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <thread>

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, __LINE__,      \
              #cond);                                                      \
      abort();                                                             \
    }                                                                      \
  } while (0)

namespace {

constexpr int64_t FP = 10000;  // kScale

void test_accounting() {
  void* s = sched_create();
  int64_t totals[2] = {4 * FP, 1 * FP};  // 4 CPU, 1 TPU
  CHECK(sched_upsert_node(s, 0, totals, 2) == 0);
  int64_t demand[2] = {2 * FP, 0};
  CHECK(sched_acquire(s, 0, demand, 2) == 0);
  int64_t avail[2] = {0, 0};
  sched_available(s, 0, avail, 2);
  CHECK(avail[0] == 2 * FP && avail[1] == 1 * FP);
  CHECK(sched_utilization(s, 0) == FP / 2);  // 50% on CPU axis
  // insufficient
  int64_t big[2] = {3 * FP, 0};
  CHECK(sched_acquire(s, 0, big, 2) == -1);
  // release clamps at total
  int64_t huge[2] = {100 * FP, 100 * FP};
  sched_release(s, 0, huge, 2);
  sched_available(s, 0, avail, 2);
  CHECK(avail[0] == 4 * FP && avail[1] == 1 * FP);
  // force-acquire oversubscribes (blocked-task re-acquire path)
  sched_acquire_force(s, 0, huge, 2);
  sched_available(s, 0, avail, 2);
  CHECK(avail[0] == 4 * FP - 100 * FP);
  sched_destroy(s);
  fprintf(stderr, "test_accounting OK\n");
}

void test_hybrid_pack_then_spread() {
  void* s = sched_create();
  int64_t totals[1] = {10 * FP};
  CHECK(sched_upsert_node(s, 0, totals, 1) == 0);
  CHECK(sched_upsert_node(s, 1, totals, 1) == 0);
  // node0 at 20%, node1 at 50%
  int64_t d2[1] = {2 * FP}, d5[1] = {5 * FP};
  CHECK(sched_acquire(s, 0, d2, 1) == 0);
  CHECK(sched_acquire(s, 1, d5, 1) == 0);
  // below the 70% threshold both are packable: MOST utilized (node1) wins
  int64_t d1[1] = {1 * FP};
  CHECK(sched_pick_and_acquire(s, d1, 1, 7000, -1) == 1);
  // push node1 over the threshold: utilization 60%+... fill to 90%
  int64_t d3[1] = {3 * FP};
  CHECK(sched_acquire(s, 1, d3, 1) == 0);  // node1 now 90%
  // node1 >= threshold, node0 (20%) below: pack picks node0
  CHECK(sched_pick_and_acquire(s, d1, 1, 7000, -1) == 0);
  sched_destroy(s);
  fprintf(stderr, "test_hybrid_pack_then_spread OK\n");
}

void test_spread_when_all_above_threshold() {
  void* s = sched_create();
  int64_t totals[1] = {10 * FP};
  CHECK(sched_upsert_node(s, 0, totals, 1) == 0);
  CHECK(sched_upsert_node(s, 1, totals, 1) == 0);
  int64_t d8[1] = {8 * FP}, d9[1] = {9 * FP};
  CHECK(sched_acquire(s, 0, d8, 1) == 0);  // 80%
  CHECK(sched_acquire(s, 1, d9, 1) == 0);  // 90%
  // both above a 50% threshold: spread to LEAST utilized (node0)
  int64_t d1[1] = {1 * FP};
  CHECK(sched_pick_and_acquire(s, d1, 1, 5000, -1) == 0);
  sched_destroy(s);
  fprintf(stderr, "test_spread_when_all_above_threshold OK\n");
}

void test_prefer_and_feasible_and_dead() {
  void* s = sched_create();
  int64_t totals[1] = {4 * FP};
  CHECK(sched_upsert_node(s, 0, totals, 1) == 0);
  CHECK(sched_upsert_node(s, 1, totals, 1) == 0);
  int64_t d1[1] = {1 * FP};
  // equal utilization: prefer_idx breaks the tie
  CHECK(sched_pick_and_acquire(s, d1, 1, 7000, 1) == 1);
  // feasibility looks at TOTALS, not current availability
  int64_t d6[1] = {6 * FP};
  CHECK(sched_feasible(s, d6, 1) == 0);
  int64_t d4[1] = {4 * FP};
  CHECK(sched_feasible(s, d4, 1) == 1);
  // dead nodes are invisible
  CHECK(sched_remove_node(s, 0) == 0);
  CHECK(sched_remove_node(s, 1) == 0);
  CHECK(sched_pick_and_acquire(s, d1, 1, 7000, -1) == -1);
  CHECK(sched_feasible(s, d4, 1) == 0);
  sched_destroy(s);
  fprintf(stderr, "test_prefer_and_feasible_and_dead OK\n");
}

void test_concurrent_acquire_release() {
  void* s = sched_create();
  int64_t totals[1] = {1000 * FP};
  CHECK(sched_upsert_node(s, 0, totals, 1) == 0);
  CHECK(sched_upsert_node(s, 1, totals, 1) == 0);
  std::atomic<int> acquired{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; t++) {
    threads.emplace_back([s, &acquired]() {
      int64_t d[1] = {1 * FP};
      for (int i = 0; i < 2000; i++) {
        int node = sched_pick_and_acquire(s, d, 1, 7000, -1);
        if (node >= 0) {
          acquired++;
          sched_release(s, node, d, 1);
          acquired--;
        }
        if (i % 100 == 0) sched_utilization(s, node >= 0 ? node : 0);
      }
    });
  }
  for (auto& th : threads) th.join();
  CHECK(acquired.load() == 0);
  // all reservations returned: both nodes fully available
  int64_t avail[1];
  sched_available(s, 0, avail, 1);
  int64_t a0 = avail[0];
  sched_available(s, 1, avail, 1);
  CHECK(a0 == 1000 * FP && avail[0] == 1000 * FP);
  sched_destroy(s);
  fprintf(stderr, "test_concurrent_acquire_release OK\n");
}

}  // namespace

int main() {
  test_accounting();
  test_hybrid_pack_then_spread();
  test_spread_when_all_above_threshold();
  test_prefer_and_feasible_and_dead();
  test_concurrent_acquire_release();
  fprintf(stderr, "scheduler_test: ALL OK\n");
  return 0;
}
