// Native scheduling core: fixed-point resource accounting + hybrid policy.
//
// TPU-native analog of the reference's raylet scheduling layer
// (reference: src/ray/raylet/scheduling/fixed_point.h FixedPoint;
// cluster_resource_manager + scheduling/policy/hybrid_scheduling_policy.h:48
// — pack onto the best-utilized feasible node below a utilization
// threshold, else spread to the least utilized; top-k randomization to
// avoid herding).  The head server calls this through ctypes for every
// placement decision; resource names are interned to dense indices on the
// Python side.
//
// Fixed point: int64 at 1e4 scale (reference uses the same 1e4 factor).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kScale = 10000;
constexpr int kMaxResources = 128;

struct Node {
  bool alive = false;
  int64_t total[kMaxResources] = {0};
  int64_t available[kMaxResources] = {0};
};

struct Scheduler {
  std::vector<Node> nodes;
  std::mutex mu;
  std::mt19937 rng{12345};
};

int64_t util_of(const Node& n) {
  // max over resources of used/total, at kScale
  int64_t best = 0;
  for (int i = 0; i < kMaxResources; ++i) {
    if (n.total[i] > 0) {
      int64_t used = n.total[i] - n.available[i];
      int64_t u = used * kScale / n.total[i];
      if (u > best) best = u;
    }
  }
  return best;
}

bool fits(const Node& n, const int64_t* demand, int nd) {
  for (int i = 0; i < nd; ++i) {
    if (demand[i] > 0 && n.available[i] < demand[i]) return false;
  }
  return true;
}

bool total_fits(const Node& n, const int64_t* demand, int nd) {
  for (int i = 0; i < nd; ++i) {
    if (demand[i] > 0 && n.total[i] < demand[i]) return false;
  }
  return true;
}

}  // namespace

extern "C" {

void* sched_create() { return new Scheduler(); }

void sched_destroy(void* h) { delete static_cast<Scheduler*>(h); }

// Ensure capacity for node_idx and set its totals (also resets availability
// to total minus current usage delta — used at (re)registration).
int sched_upsert_node(void* h, int node_idx, const int64_t* totals, int n) {
  if (node_idx < 0 || n > kMaxResources) return -1;
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if ((size_t)node_idx >= s->nodes.size()) s->nodes.resize(node_idx + 1);
  Node& node = s->nodes[node_idx];
  for (int i = 0; i < n; ++i) {
    int64_t used = node.alive ? node.total[i] - node.available[i] : 0;
    node.total[i] = totals[i];
    node.available[i] = totals[i] - used;
  }
  node.alive = true;
  return 0;
}

int sched_remove_node(void* h, int node_idx) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if ((size_t)node_idx >= s->nodes.size()) return -1;
  s->nodes[node_idx].alive = false;
  return 0;
}

// Try to reserve demand on a node. 0 = ok, -1 = insufficient.
int sched_acquire(void* h, int node_idx, const int64_t* demand, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if ((size_t)node_idx >= s->nodes.size()) return -1;
  Node& node = s->nodes[node_idx];
  if (!node.alive || !fits(node, demand, n)) return -1;
  for (int i = 0; i < n; ++i) node.available[i] -= demand[i];
  return 0;
}

// Force-reserve (oversubscription allowed — blocked-task re-acquire path).
void sched_acquire_force(void* h, int node_idx, const int64_t* demand, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if ((size_t)node_idx >= s->nodes.size()) return;
  Node& node = s->nodes[node_idx];
  for (int i = 0; i < n; ++i) node.available[i] -= demand[i];
}

void sched_release(void* h, int node_idx, const int64_t* demand, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if ((size_t)node_idx >= s->nodes.size()) return;
  Node& node = s->nodes[node_idx];
  for (int i = 0; i < n; ++i) {
    node.available[i] += demand[i];
    if (node.available[i] > node.total[i]) node.available[i] = node.total[i];
  }
}

int64_t sched_utilization(void* h, int node_idx) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if ((size_t)node_idx >= s->nodes.size()) return 0;
  return util_of(s->nodes[node_idx]);
}

void sched_available(void* h, int node_idx, int64_t* out, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if ((size_t)node_idx >= s->nodes.size()) return;
  std::memcpy(out, s->nodes[node_idx].available, n * sizeof(int64_t));
}

// Hybrid policy: among feasible nodes with utilization < threshold pick the
// MOST utilized (pack); if none below threshold, pick the LEAST utilized
// (spread).  Returns node idx and reserves, or -1 if none feasible.
// prefer_idx (e.g. the head/local node) wins ties, as in the reference's
// local-node preference.
int sched_pick_and_acquire(void* h, const int64_t* demand, int n,
                           int64_t spread_threshold_fp, int prefer_idx) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  int best_pack = -1, best_spread = -1;
  int64_t best_pack_util = -1, best_spread_util = INT64_MAX;
  for (size_t i = 0; i < s->nodes.size(); ++i) {
    Node& node = s->nodes[i];
    if (!node.alive || !fits(node, demand, n)) continue;
    int64_t u = util_of(node);
    if (u < spread_threshold_fp) {
      if (u > best_pack_util ||
          (u == best_pack_util && (int)i == prefer_idx)) {
        best_pack_util = u;
        best_pack = (int)i;
      }
    }
    if (u < best_spread_util || (u == best_spread_util && (int)i == prefer_idx)) {
      best_spread_util = u;
      best_spread = (int)i;
    }
  }
  int pick = best_pack >= 0 ? best_pack : best_spread;
  if (pick < 0) return -1;
  Node& node = s->nodes[pick];
  for (int i = 0; i < n; ++i) node.available[i] -= demand[i];
  return pick;
}

// Any alive node whose TOTAL capacity could ever fit the demand?
int sched_feasible(void* h, const int64_t* demand, int n) {
  auto* s = static_cast<Scheduler*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  for (auto& node : s->nodes) {
    if (node.alive && total_fits(node, demand, n)) return 1;
  }
  return 0;
}

}  // extern "C"
