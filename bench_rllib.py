"""RLlib PPO env-steps/s/chip benchmark (BASELINE config #3).

Product path: PPO CNN policy at Atari frame shape (84x84x4 uint8),
rollout worker ACTORS stepping vectorized pixel envs on host CPU, the
central learner's pjit update running on the TPU chip — the TPU-native
realization of the reference's "PPO Atari CNN policy, rollout + TPU
learner actors" acceptance config.  The reference publishes no absolute
env-steps/s number (BASELINE.json "published": {}), so vs_baseline is
reported against the north-star existence requirement (1.0 = the number
exists and the task learns).

Prints ONE JSON line like bench.py.  Run with the ambient env (sole TPU
claimant): python bench_rllib.py
"""

import json
import time

import numpy as np


def main():
    import jax

    platform = jax.devices()[0].platform
    import ray_tpu
    from ray_tpu.rllib.algorithm import AlgorithmConfig
    from ray_tpu.rllib.env import SyntheticPixelEnv

    num_workers = 2
    num_envs = 32
    fragment = 50  # per-env steps per iteration

    def creator():
        return SyntheticPixelEnv(num_envs=num_envs, shaped=True, seed=11)

    ray_tpu.init(num_cpus=max(4, num_workers + 1))
    try:
        algo = (
            AlgorithmConfig()
            .environment(creator)
            .rollouts(num_rollout_workers=num_workers, num_envs_per_worker=num_envs)
            .training(
                lr=1e-3,
                train_batch_size=num_workers * num_envs * fragment,
                rollout_fragment_length=fragment,
                sgd_minibatch_size=800,
                num_sgd_iter=2,
                model={"type": "cnn"},
            )
            .build()
        )
        # warmup: compile learner + actor forwards
        r = algo.train()
        iters = 5
        t0 = time.time()
        steps = 0
        reward = 0.0
        for _ in range(iters):
            r = algo.train()
            steps += r["timesteps_this_iter"]
            reward = r["episode_reward_mean"]
        dt = time.time() - t0
        env_steps_per_sec = steps / dt

        # learner-only ceiling: how many env-steps/s the TPU update itself
        # can consume at this batch shape (rollout-decoupled upper bound)
        from ray_tpu.rllib.sample_batch import (
            ACTIONS,
            ADVANTAGES,
            LOGPS,
            OBS,
            RETURNS,
            SampleBatch,
        )

        rng = np.random.default_rng(0)
        B = num_workers * num_envs * fragment
        batch = SampleBatch(
            {
                OBS: rng.integers(0, 256, (B, 84, 84, 4), dtype=np.uint8),
                ACTIONS: rng.integers(0, 3, B),
                LOGPS: np.full(B, -1.0986, np.float32),
                ADVANTAGES: rng.standard_normal(B).astype(np.float32),
                RETURNS: rng.standard_normal(B).astype(np.float32),
            }
        )
        # staged path: ONE host→device transfer, all SGD epochs on-device
        staged = algo.policy.load_batch(batch)
        algo.policy.learn_on_loaded_batch(staged, algo.config.num_sgd_iter, 800)  # compile
        t0 = time.time()
        n_up = 10
        for _ in range(n_up):
            staged = algo.policy.load_batch(batch)
            algo.policy.learn_on_loaded_batch(staged, algo.config.num_sgd_iter, 800)
        learner_dt = time.time() - t0
        # each loaded-batch call consumes B fresh env steps
        learner_steps_per_sec = n_up * B / learner_dt

        # device-resident variant: the SAME staged batch re-used, so the
        # number isolates the jitted update from the H2D transfer (which
        # rides the axon tunnel here and dominates the loaded-batch form)
        t0 = time.time()
        for _ in range(n_up):
            algo.policy.learn_on_loaded_batch(staged, algo.config.num_sgd_iter, 800)
        resident_steps_per_sec = n_up * B / (time.time() - t0)

        obs_transfer = _bench_obs_transfer(B)

        sac = _bench_sac()

        result = (
                {
                    "metric": "ppo_pixel_cnn_env_steps_per_sec_per_chip",
                    "value": round(env_steps_per_sec, 1),
                    "unit": "env_steps/s/chip",
                    # the reference publishes NO absolute env-steps/s for
                    # this config (BASELINE.json published: {}): 1.0 here
                    # means "the required capability exists and learns",
                    # not a measured speedup over a reference number
                    "vs_baseline": 1.0,
                    "vs_baseline_basis": "existence (reference publishes no absolute number)",
                    "platform": platform,
                    "path": "rollout_actors+tpu_learner",
                    "learner_only_env_steps_per_sec": round(learner_steps_per_sec, 1),
                    "learner_device_resident_env_steps_per_sec": round(
                        resident_steps_per_sec, 1
                    ),
                    "num_rollout_workers": num_workers,
                    "num_envs_per_worker": num_envs,
                    "obs_shape": [84, 84, 4],
                    "episode_reward_mean": round(reward, 3),
                    "obs_transfer_MBps": obs_transfer,
                    "sac_pendulum": sac,
                }
        )
        with open("RLBENCH_r05.json", "w") as f:
            json.dump(result, f, indent=1)
        print(json.dumps(result))
        algo.stop()
    finally:
        ray_tpu.shutdown()


def _bench_obs_transfer(batch_size):
    """Rollout→learner obs-batch transfer rate, host plane vs device tier.

    The PPO iteration moves one ``(B, 84, 84, 4)`` uint8 obs batch from the
    rollout side to the learner every train() call; this times exactly that
    movement as a cross-process put+get pair under both tiers and reports
    MB/s for each plus the quotient (core/DEVICE_TIER.md)."""
    import ray_tpu

    obs = np.random.default_rng(3).integers(
        0, 256, (batch_size, 84, 84, 4), dtype=np.uint8
    )
    mb = obs.nbytes / (1024 * 1024)

    @ray_tpu.remote
    def consume(x):
        a = np.asarray(x)
        return int(a[::17, 0, 0, 0].astype(np.int64).sum())

    want = int(obs[::17, 0, 0, 0].astype(np.int64).sum())
    out = {}
    for label, tier in (("host", "host"), ("device", "device")):
        # warm the pull path, then keep the best of 3 (same-box quotient)
        best = 0.0
        for _ in range(3):
            t0 = time.time()
            ref = ray_tpu.put(obs, tier=tier)
            got = ray_tpu.get(consume.remote(ref), timeout=300)
            best = max(best, mb / (time.time() - t0))
            assert got == want, f"obs transfer corrupted on {tier} tier"
        out[label] = round(best, 1)
    out["speedup"] = round(out["device"] / max(out["host"], 1e-9), 2)
    return out


def _bench_sac():
    """Continuous-control throughput: SAC on the vectorized Pendulum —
    acting + replay + jitted twin-Q/actor/alpha updates, end to end
    (VERDICT r4 #3's env-steps/s evidence)."""
    from ray_tpu.rllib.env import PendulumEnv
    from ray_tpu.rllib.replay_buffer import ReplayBuffer
    from ray_tpu.rllib.sac import SACPolicy
    from ray_tpu.rllib.sample_batch import (
        ACTIONS,
        DONES,
        NEXT_OBS,
        OBS,
        REWARDS,
        SampleBatch,
    )

    env = PendulumEnv(num_envs=16, seed=0)
    pol = SACPolicy(
        obs_shape=(3,), act_dim=1,
        action_low=env.action_space.low, action_high=env.action_space.high,
        hidden=(128, 128), seed=0,
    )
    buf = ReplayBuffer(100_000, seed=0)
    obs = env.reset(seed=0)
    ep_rew = np.zeros(16)
    ep_hist = []
    # warmup fills the buffer + compiles act/update
    rng = np.random.default_rng(0)
    for _ in range(80):
        raw = rng.uniform(-1, 1, (16, 1)).astype(np.float32)
        nobs, rew, done, _ = env.step(pol._center + pol._scale * raw)
        buf.add(SampleBatch({OBS: obs, ACTIONS: raw, REWARDS: rew,
                             NEXT_OBS: nobs, DONES: done.astype(np.float32)}))
        obs = nobs
    pol.learn_on_batch(buf.sample(128))
    t0 = time.time()
    env_steps = 0
    iters = 500
    for _ in range(iters):
        env_a, raw = pol.compute_actions(obs)
        nobs, rew, done, _ = env.step(env_a)
        buf.add(SampleBatch({OBS: obs, ACTIONS: raw, REWARDS: rew,
                             NEXT_OBS: nobs, DONES: done.astype(np.float32)}))
        env_steps += 16
        ep_rew += rew
        for i in np.nonzero(done)[0]:
            ep_hist.append(ep_rew[i])
            ep_rew[i] = 0.0
        obs = nobs
        for _ in range(4):
            metrics = pol.learn_on_batch(buf.sample(128))
    dt = time.time() - t0
    return {
        "env_steps_per_sec": round(env_steps / dt, 1),
        "grad_updates_per_sec": round(iters * 4 / dt, 1),
        "updates_per_env_step": 0.25,
        "episode_reward_mean": round(float(np.mean(ep_hist[-10:])) if ep_hist else 0.0, 1),
        "alpha": round(metrics["alpha"], 4),
    }


if __name__ == "__main__":
    main()
