"""RLlib PPO env-steps/s/chip benchmark (BASELINE config #3).

Product path: PPO CNN policy at Atari frame shape (84x84x4 uint8),
rollout worker ACTORS stepping vectorized pixel envs on host CPU, the
central learner's pjit update running on the TPU chip — the TPU-native
realization of the reference's "PPO Atari CNN policy, rollout + TPU
learner actors" acceptance config.  The reference publishes no absolute
env-steps/s number (BASELINE.json "published": {}), so vs_baseline is
reported against the north-star existence requirement (1.0 = the number
exists and the task learns).

Prints ONE JSON line like bench.py.  Run with the ambient env (sole TPU
claimant): python bench_rllib.py
"""

import json
import time

import numpy as np


def main():
    import jax

    platform = jax.devices()[0].platform
    import ray_tpu
    from ray_tpu.rllib.algorithm import AlgorithmConfig
    from ray_tpu.rllib.env import SyntheticPixelEnv

    num_workers = 2
    num_envs = 32
    fragment = 50  # per-env steps per iteration

    def creator():
        return SyntheticPixelEnv(num_envs=num_envs, shaped=True, seed=11)

    ray_tpu.init(num_cpus=max(4, num_workers + 1))
    try:
        algo = (
            AlgorithmConfig()
            .environment(creator)
            .rollouts(num_rollout_workers=num_workers, num_envs_per_worker=num_envs)
            .training(
                lr=1e-3,
                train_batch_size=num_workers * num_envs * fragment,
                rollout_fragment_length=fragment,
                sgd_minibatch_size=800,
                num_sgd_iter=2,
                model={"type": "cnn"},
            )
            .build()
        )
        # warmup: compile learner + actor forwards
        r = algo.train()
        iters = 5
        t0 = time.time()
        steps = 0
        reward = 0.0
        for _ in range(iters):
            r = algo.train()
            steps += r["timesteps_this_iter"]
            reward = r["episode_reward_mean"]
        dt = time.time() - t0
        env_steps_per_sec = steps / dt

        # learner-only ceiling: how many env-steps/s the TPU update itself
        # can consume at this batch shape (rollout-decoupled upper bound)
        from ray_tpu.rllib.sample_batch import (
            ACTIONS,
            ADVANTAGES,
            LOGPS,
            OBS,
            RETURNS,
            SampleBatch,
        )

        rng = np.random.default_rng(0)
        B = num_workers * num_envs * fragment
        batch = SampleBatch(
            {
                OBS: rng.integers(0, 256, (B, 84, 84, 4), dtype=np.uint8),
                ACTIONS: rng.integers(0, 3, B),
                LOGPS: np.full(B, -1.0986, np.float32),
                ADVANTAGES: rng.standard_normal(B).astype(np.float32),
                RETURNS: rng.standard_normal(B).astype(np.float32),
            }
        )
        # staged path: ONE host→device transfer, all SGD epochs on-device
        staged = algo.policy.load_batch(batch)
        algo.policy.learn_on_loaded_batch(staged, algo.config.num_sgd_iter, 800)  # compile
        t0 = time.time()
        n_up = 10
        for _ in range(n_up):
            staged = algo.policy.load_batch(batch)
            algo.policy.learn_on_loaded_batch(staged, algo.config.num_sgd_iter, 800)
        learner_dt = time.time() - t0
        # each loaded-batch call consumes B fresh env steps
        learner_steps_per_sec = n_up * B / learner_dt

        print(
            json.dumps(
                {
                    "metric": "ppo_pixel_cnn_env_steps_per_sec_per_chip",
                    "value": round(env_steps_per_sec, 1),
                    "unit": "env_steps/s/chip",
                    "vs_baseline": 1.0,
                    "platform": platform,
                    "path": "rollout_actors+tpu_learner",
                    "learner_only_env_steps_per_sec": round(learner_steps_per_sec, 1),
                    "num_rollout_workers": num_workers,
                    "num_envs_per_worker": num_envs,
                    "obs_shape": [84, 84, 4],
                    "episode_reward_mean": round(reward, 3),
                }
            )
        )
        algo.stop()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
