"""Serve LLM benchmark (BASELINE config #5 shape): Llama decode on the
TPU behind a @serve.batch deployment — tokens/s + request p50/p99 at
several offered loads, autoscaling engaged.

Product path: client → DeploymentHandle → TPU-claiming replica actor →
the tp-sharded ShardedLLM engine (ray_tpu/serve/llm.py, tp=1 on this
one-chip host; the SAME code path the multi-chip dryrun proves at
llama2_7b shape) — ONE jitted prefill+decode program per coalesced
batch with the KV cache donated.  Model: a llama-family config sized
for one 16G v5e chip in bf16 (llama2_7b bf16 weights alone are
~13.5 GB — 7B serving is the tp mesh story).  Reference analog:
python/ray/serve/benchmarks + serve/batching.py:46.

A second section (SERVE_BENCH_MIXED=1, default) replays one seeded
mixed-length Poisson trace against BOTH the static @serve.batch path and
the continuous-batching engine (ray_tpu/serve/engine/) and emits both
rows in the same JSON — per-class p50/p99, tokens/s, and the engine's
real TTFT/TPOT percentiles.  The legacy sweep stays untouched for
round-over-round comparability.

Writes SERVE_BENCH_r05.json and prints one JSON line.
"""

import json
import os
import time

import numpy as np

MAX_SEQ = 256
NEW_TOKENS = 32
MAX_BATCH = int(os.environ.get("SERVE_BENCH_MAX_BATCH", "8"))
MODEL = os.environ.get("SERVE_BENCH_MODEL", "llama_3b")

# ---- mixed-length Poisson workload (static vs continuous-batching engine)
# Short + long prompts interleaved at Poisson arrivals — the head-of-line
# blocking shape that saturated the static path in SERVE_BENCH_r04.  The
# tiny model keeps this section cheap on any backend (the comparison is
# about SCHEDULING, not FLOPs); set SERVE_BENCH_MIXED_MODEL to bench a
# real config, SERVE_BENCH_MIXED=0 to skip.
MIXED = os.environ.get("SERVE_BENCH_MIXED", "1") not in ("0", "false")
MIXED_MODEL = os.environ.get("SERVE_BENCH_MIXED_MODEL", "tiny")
MIXED_RPS = float(os.environ.get("SERVE_BENCH_MIXED_RPS", "72"))
MIXED_N = int(os.environ.get("SERVE_BENCH_MIXED_N", "240"))
# heterogeneous budgets are THE continuous-batching case: the static
# whole-request batch decodes EVERY member to the longest budget (its
# wire has one new_tokens), while the engine retires each sequence at
# its own — a short request stops at 8 tokens instead of riding out 48
MIXED_SHORT, MIXED_LONG = 4, 96  # prompt lengths
MIXED_NEW = {"short": 8, "long": 96}  # per-class token budgets
MIXED_LONG_FRAC = 0.25


# ---- fleet survival section (serve/FLEET.md): seeded Poisson stream
# spike against an SLO-autoscaled engine fleet — scale-out reaction
# time, mid-stream failover count under a replica kill, and client-side
# TTFT p99 with/without the kill.  Tiny model: the section measures the
# CONTROL plane (scaling, drain, failover), not FLOPs.
FLEET = os.environ.get("SERVE_BENCH_FLEET", "1") not in ("0", "false")
FLEET_N = int(os.environ.get("SERVE_BENCH_FLEET_N", "24"))
FLEET_RPS = float(os.environ.get("SERVE_BENCH_FLEET_RPS", "16"))
FLEET_NEW = int(os.environ.get("SERVE_BENCH_FLEET_NEW", "48"))


def _poisson_schedule(rng, n, rate):
    """Deterministic (seeded) arrival schedule replayed identically
    against both systems: [(t_offset, class, prompt_tokens)]."""
    t = 0.0
    sched = []
    for _ in range(n):
        t += rng.exponential(1.0 / rate)
        if rng.random() < MIXED_LONG_FRAC:
            cls, plen = "long", MIXED_LONG
        else:
            cls, plen = "short", MIXED_SHORT
        sched.append((t, cls, [int(x) for x in rng.integers(1, 255, plen)]))
    return sched


def _run_mixed(ray_tpu, handle, sched, per_request_budget: bool):
    """Replay the schedule open-loop (arrivals don't wait for
    completions — queueing shows up as latency, exactly like production
    traffic) and return per-class latency percentiles + useful-tokens/s.
    ``per_request_budget``: the engine honors a budget per request; the
    static path can't (one new_tokens per deployment) — that asymmetry
    is the system under test, not a bench artifact."""
    lat: dict = {}
    inflight: dict = {}

    def _reap(timeout):
        ready, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=timeout)
        for r in ready:
            t_sub, c = inflight.pop(r)
            ray_tpu.get(r, timeout=120)
            lat.setdefault(c, []).append(time.time() - t_sub)

    t0 = time.time()
    for t_off, cls, prompt in sched:
        while time.time() - t0 < t_off:
            if inflight:
                _reap(max(0.001, t_off - (time.time() - t0)))
            else:
                time.sleep(min(0.002, max(0.0, t_off - (time.time() - t0))))
        if per_request_budget:
            payload = {"prompt": prompt, "max_new_tokens": MIXED_NEW[cls]}
        else:
            payload = prompt
        inflight[handle.remote(payload)] = (time.time(), cls)
    while inflight:
        _reap(600)
    dt = time.time() - t0
    useful = sum(MIXED_NEW[cls] for _, cls, _ in sched)
    out = {"tokens_per_sec": round(useful / dt, 1)}
    for cls, vals in lat.items():
        ms = np.asarray(vals) * 1000
        out[cls] = {
            "n": len(vals),
            "p50_ms": round(float(np.percentile(ms, 50)), 1),
            "p99_ms": round(float(np.percentile(ms, 99)), 1),
        }
    return out


def mixed_workload_bench(ray_tpu, serve):
    """Static whole-request batching vs the continuous-batching engine on
    one seeded mixed-length Poisson trace; one JSON blob with both."""
    from ray_tpu.serve.llm import engine_llm_deployment, llm_deployment

    budget_max = max(MIXED_NEW.values())
    max_seq = MIXED_LONG + budget_max + 16
    sched = _poisson_schedule(np.random.default_rng(0), MIXED_N, MIXED_RPS)

    static = serve.run(
        llm_deployment(
            MIXED_MODEL, max_seq_len=max_seq, new_tokens=budget_max,
            max_batch_size=4, batch_wait_timeout_s=0.01, num_tpus=0, tp=1,
        ).options(name="llm_static_mixed").bind()
    )
    # warm every (batch size, padded prompt len) shape the trace can hit:
    # batches pad to their longest member, so P ∈ {short, long} only
    for plen in (MIXED_SHORT, MIXED_LONG):
        for b in range(1, 5):
            for _ in range(2):
                ray_tpu.get(
                    [static.remote([1] * plen) for _ in range(b)], timeout=1800
                )
    static_row = _run_mixed(ray_tpu, static, sched, per_request_budget=False)
    serve.delete("llm_static_mixed")

    engine = serve.run(
        engine_llm_deployment(
            MIXED_MODEL, max_seq_len=max_seq, new_tokens=budget_max,
            num_slots=8, page_size=16, prefill_chunk=16, num_tpus=0, tp=1,
        ).options(name="llm_engine_mixed").bind()
    )
    ray_tpu.get(engine.remote([1] * MIXED_SHORT), timeout=1800)  # warm
    engine_row = _run_mixed(ray_tpu, engine, sched, per_request_budget=True)

    # engine-side TTFT/TPOT are real per-request measurements from the
    # serve trace plane (first token host-visible at the prefill/decode
    # boundary)
    ttft = tpot = {}
    try:
        from ray_tpu.experimental.state import summarize_workloads

        s = summarize_workloads("serve")
        ttft = s.get("ttft", {}).get("llm_engine_mixed") or {}
        tpot = s.get("tpot", {}).get("llm_engine_mixed") or {}
    except Exception as e:  # noqa: BLE001 — bench must still emit a row
        print(f"mixed serve-trace summary unavailable: {e}")
    serve.delete("llm_engine_mixed")

    sp99 = static_row.get("short", {}).get("p99_ms") or 0
    ep99 = engine_row.get("short", {}).get("p99_ms") or 0
    return {
        "model": MIXED_MODEL,
        "arrival_rate_rps": MIXED_RPS,
        "requests": MIXED_N,
        "new_tokens": dict(MIXED_NEW),
        "prompt_lens": {"short": MIXED_SHORT, "long": MIXED_LONG},
        "long_fraction": MIXED_LONG_FRAC,
        "static": static_row,
        "engine": engine_row,
        "engine_ttft_ms_p50": round(ttft["p50"] * 1e3, 1) if ttft else None,
        "engine_ttft_ms_p99": round(ttft["p99"] * 1e3, 1) if ttft else None,
        "engine_tpot_ms_p50": round(tpot["p50"] * 1e3, 2) if tpot else None,
        "engine_tpot_ms_p99": round(tpot["p99"] * 1e3, 2) if tpot else None,
        # the headline: short-request tail latency under long-prompt
        # interference, engine vs static (ROADMAP item 1's p99 cliff)
        "short_p99_ratio_engine_vs_static": round(ep99 / sp99, 3) if sp99 else None,
    }


def _fleet_busy_replica(ray_tpu, name):
    """Index of the replica actively decoding (slots_active > 0) — the
    load snapshots lag, so ask the engines directly."""
    from ray_tpu.serve.api import CONTROLLER_NAME

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    info = ray_tpu.get(controller.get_handles.remote(name), timeout=30)
    for i, r in enumerate(info["replicas"]):
        try:
            st = ray_tpu.get(
                r.handle_request.remote("engine_stats", (), {}), timeout=30
            )
        except Exception:  # noqa: BLE001 — a booting/dead replica just isn't busy
            continue
        if st.get("slots_active", 0.0) > 0:
            return i
    return -1


def _fleet_stream_trace(ray_tpu, handle, sched, name, kill_at=None):
    """Replay a seeded Poisson arrival schedule as token STREAMS (one
    thread per request, arrivals open-loop), recording client-side TTFT
    per stream.  ``kill_at``: after that many launches, SIGKILL the busy
    replica — every stream must still complete its full budget through
    mid-stream failover."""
    import threading

    from ray_tpu.util import chaos_api

    results: list = []
    errors: list = []
    lock = threading.Lock()

    def _one(prompt):
        t0 = time.time()
        ttft, n = None, 0
        try:
            for fr in handle.stream_tokens(
                {"prompt": prompt, "max_new_tokens": FLEET_NEW}
            ):
                if ttft is None:
                    ttft = time.time() - t0
                n += len(fr)
            with lock:
                results.append((ttft, n))
        except Exception as e:  # noqa: BLE001 — a dropped stream IS the result
            with lock:
                errors.append(f"{type(e).__name__}: {e}")

    threads = []
    t0 = time.time()
    for i, (t_off, _cls, prompt) in enumerate(sched):
        while time.time() - t0 < t_off:
            time.sleep(min(0.002, max(0.0, t_off - (time.time() - t0))))
        th = threading.Thread(target=_one, args=(prompt,), daemon=True)
        th.start()
        threads.append(th)
        if kill_at is not None and i == kill_at:
            idx = _fleet_busy_replica(ray_tpu, name)
            if idx >= 0:
                chaos_api.kill_replica(name, idx)
    for th in threads:
        th.join(600)
    ttfts = np.asarray([t for t, _ in results if t is not None]) * 1000
    return {
        "completed": len(results),
        "full_budget": sum(1 for _, n in results if n == FLEET_NEW),
        "errors": errors,
        "ttft_ms_p99": round(float(np.percentile(ttfts, 99)), 1)
        if len(ttfts)
        else None,
    }


def fleet_survival_bench(ray_tpu, serve):
    """Fleet survival headline numbers (serve/FLEET.md): one seeded
    Poisson stream spike drives an SLO-autoscaled 2-replica engine
    fleet.  Phase 1 (spike, no kill): the spike breaches an aggressive
    latency SLO and the watchdog scales 1→2 — reaction time is spike
    start to the controller's target moving.  Phase 2 (kill): the same
    trace replays against the 2-replica fleet with the busy replica
    SIGKILLed mid-stream — failovers resume every stream from its
    delivered frontier, and the TTFT p99 delta vs phase 1 prices the
    survival machinery."""
    import threading

    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.serve.api import CONTROLLER_NAME
    from ray_tpu.serve.llm import engine_llm_deployment
    from ray_tpu.util import slo_api

    cfg = LlamaConfig(
        dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        vocab_size=256, compute_dtype=jnp.float32, max_seq_len=128,
    )
    dep = engine_llm_deployment(
        cfg, new_tokens=FLEET_NEW, num_slots=4, page_size=16,
        prefill_chunk=16, num_tpus=0, tp=1, name="llm_fleet",
    )
    handle = serve.run(dep.bind())  # 1 replica; the SLO scales it out
    # warm the compile before the clock starts
    _ = [t for fr in handle.stream_tokens(
        {"prompt": [1, 2, 3], "max_new_tokens": 4}) for t in fr]
    rng = np.random.default_rng(7)
    sched = [
        (t, c, [int(x) for x in rng.integers(1, 255, 8)])
        for (t, c, _p) in _poisson_schedule(rng, FLEET_N, FLEET_RPS)
    ]
    controller = ray_tpu.get_actor(CONTROLLER_NAME)

    def _target():
        deps = ray_tpu.get(controller.list_deployments.remote(), timeout=30)
        return deps.get("llm_fleet", {}).get("target", 0)

    # any completed request breaches a 1µs p50 bound → sustained burn →
    # the watchdog publishes ONE scale_out directive per cooldown window
    slo_api.set_slos([{
        "name": "fleet_bench_latency",
        "metric": "ray_tpu_serve_request_seconds",
        "tags": {"deployment": "llm_fleet"},
        "quantile": 0.5,
        "threshold_ms": 0.001,
        "window_s": 60,
        "scale_on_slo": {"deployment": "llm_fleet",
                         "min_replicas": 1, "max_replicas": 2},
    }])
    reaction = [None]
    spike_t0 = time.time()

    def _watch_scale():
        deadline = time.time() + 120
        while time.time() < deadline:
            if _target() >= 2:
                reaction[0] = round(time.time() - spike_t0, 2)
                return
            time.sleep(0.25)

    watcher = threading.Thread(target=_watch_scale, daemon=True)
    watcher.start()
    no_kill = _fleet_stream_trace(ray_tpu, handle, sched, "llm_fleet")
    # the watchdog evaluates windowed DELTAS per observer tick: a spike
    # that completes inside one tick leaves later deltas empty, so keep
    # a trickle flowing until the sustained burn publishes the directive
    trickle_deadline = time.time() + 90
    while reaction[0] is None and time.time() < trickle_deadline:
        try:
            ray_tpu.get(
                handle.remote({"prompt": [5, 6, 7], "max_new_tokens": 2}),
                timeout=60,
            )
        except Exception:  # noqa: BLE001 — trickle is best-effort load
            pass
        time.sleep(0.4)
    watcher.join(10)
    slo_api.clear_slos()
    # wait for the scaled-out fleet to be live before the kill phase
    deadline = time.time() + 60
    while time.time() < deadline and _target() < 2:
        time.sleep(0.5)
    with_kill = _fleet_stream_trace(
        ray_tpu, handle, sched, "llm_fleet", kill_at=FLEET_N // 3
    )
    failovers = 0
    try:
        from ray_tpu.experimental.state import summarize_workloads

        deadline = time.time() + 30
        while time.time() < deadline:
            fleet = (summarize_workloads("serve") or {}).get("fleet") or {}
            failovers = int(fleet.get("llm_fleet", {}).get("failovers_total", 0))
            if failovers:
                break
            time.sleep(0.5)
    except Exception as e:  # noqa: BLE001 — bench must still emit a row
        print(f"fleet summary unavailable: {e}")
    serve.delete("llm_fleet")
    return {
        "requests_per_phase": FLEET_N,
        "arrival_rate_rps": FLEET_RPS,
        "new_tokens": FLEET_NEW,
        "scale_out_reaction_s": reaction[0],
        "failovers": failovers,
        "ttft_ms_p99_no_kill": no_kill["ttft_ms_p99"],
        "ttft_ms_p99_with_kill": with_kill["ttft_ms_p99"],
        "no_kill": no_kill,
        "with_kill": with_kill,
    }


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # driver never claims the chip
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import llm_deployment

    ray_tpu.init(num_cpus=6, num_tpus=1)

    dep = llm_deployment(
        MODEL,
        max_seq_len=MAX_SEQ,
        new_tokens=NEW_TOKENS,
        max_batch_size=MAX_BATCH,
        batch_wait_timeout_s=0.02,
        num_tpus=1,
        autoscaling_config={
            # engaged: scales on in-flight load, pinned to the one chip
            "min_replicas": 1,
            "max_replicas": 1,
            "target_num_ongoing_requests_per_replica": 32,
        },
    )
    handle = serve.run(dep.bind())
    # warmup: compile the generation program
    t0 = time.time()
    ray_tpu.get(handle.remote(1), timeout=1800)
    compile_s = time.time() - t0
    info = ray_tpu.get(
        serve.get_deployment_handle("llm").method("info").remote(), timeout=60
    )

    loads = [4, 16, 32]
    rows = []
    for concurrency in loads:
        lat: list = []
        t0 = time.time()
        total_requests = concurrency * 4
        done = 0
        inflight = {}
        i = 0
        while done < total_requests:
            while len(inflight) < concurrency and i < total_requests:
                inflight[handle.remote(i)] = time.time()
                i += 1
            ready, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=600)
            for r in ready:
                start = inflight.pop(r)
                toks = ray_tpu.get(r, timeout=60)
                assert len(toks) == NEW_TOKENS
                lat.append(time.time() - start)
                done += 1
        dt = time.time() - t0
        lat_ms = np.asarray(lat) * 1000
        rows.append(
            {
                "offered_concurrency": concurrency,
                "tokens_per_sec": round(total_requests * NEW_TOKENS / dt, 1),
                "requests_per_sec": round(total_requests / dt, 2),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            }
        )

    # TTFT/TPOT from the serve request-trace plane: the replica stamps
    # prefill/first-token/decode boundaries per request (serve/tracing.py),
    # the head joins them next to the task flight records, and the summary
    # reports the percentiles — the baseline the continuous-batching
    # engine (ROADMAP item 1) has to beat.
    ttft = tpot = {}
    try:
        from ray_tpu.experimental.state import summarize_workloads

        serve_summary = summarize_workloads("serve")
        ttft = serve_summary.get("ttft", {}).get("llm") or {}
        tpot = serve_summary.get("tpot", {}).get("llm") or {}
    except Exception as e:  # noqa: BLE001 — bench must still emit a row
        print(f"serve-trace summary unavailable: {e}")

    result = {
        "metric": "serve_llama_decode_tokens_per_sec_per_chip",
        "value": max(r["tokens_per_sec"] for r in rows),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "vs_baseline_basis": "existence (reference publishes no absolute number)",
        "model": MODEL,
        "params_b": info["params_b"],
        "platform": info["platform"],
        "engine": "ShardedLLM tp=%d (donated-cache prefill+decode)" % info["tp"],
        "new_tokens_per_request": NEW_TOKENS,
        "batching": {"max_batch_size": MAX_BATCH, "batch_wait_timeout_s": 0.02},
        "autoscaling_engaged": True,
        "compile_s": round(compile_s, 1),
        "ttft_ms_p50": round(ttft["p50"] * 1e3, 1) if ttft else None,
        "ttft_ms_p99": round(ttft["p99"] * 1e3, 1) if ttft else None,
        "tpot_ms_p50": round(tpot["p50"] * 1e3, 2) if tpot else None,
        "tpot_ms_p99": round(tpot["p99"] * 1e3, 2) if tpot else None,
        "loads": rows,
    }
    if MIXED:
        # side-by-side static vs continuous-batching engine on one seeded
        # mixed-length Poisson trace (old sweep above kept untouched for
        # r01..r05 trajectory comparability)
        try:
            result["mixed_workload"] = mixed_workload_bench(ray_tpu, serve)
        except Exception as e:  # noqa: BLE001 — the legacy sweep's row must still land
            import traceback

            traceback.print_exc()
            result["mixed_workload"] = {"error": f"{type(e).__name__}: {e}"}
    if FLEET:
        # fleet survival: SLO-driven scale-out reaction, failover count
        # and TTFT p99 under a mid-stream replica kill (serve/FLEET.md)
        try:
            result["fleet"] = fleet_survival_bench(ray_tpu, serve)
        except Exception as e:  # noqa: BLE001 — prior sections' rows must still land
            import traceback

            traceback.print_exc()
            result["fleet"] = {"error": f"{type(e).__name__}: {e}"}
    with open("SERVE_BENCH_r05.json", "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
