"""Serve LLM benchmark (BASELINE config #5 shape): Llama decode on the
TPU behind a @serve.batch deployment — tokens/s + request p50/p99 at
several offered loads, autoscaling engaged.

Product path: client → DeploymentHandle → TPU-claiming replica actor →
the tp-sharded ShardedLLM engine (ray_tpu/serve/llm.py, tp=1 on this
one-chip host; the SAME code path the multi-chip dryrun proves at
llama2_7b shape) — ONE jitted prefill+decode program per coalesced
batch with the KV cache donated.  Model: a llama-family config sized
for one 16G v5e chip in bf16 (llama2_7b bf16 weights alone are
~13.5 GB — 7B serving is the tp mesh story).  Reference analog:
python/ray/serve/benchmarks + serve/batching.py:46.

Writes SERVE_BENCH_r05.json and prints one JSON line.
"""

import json
import os
import time

import numpy as np

MAX_SEQ = 256
NEW_TOKENS = 32
MAX_BATCH = int(os.environ.get("SERVE_BENCH_MAX_BATCH", "8"))
MODEL = os.environ.get("SERVE_BENCH_MODEL", "llama_3b")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # driver never claims the chip
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import llm_deployment

    ray_tpu.init(num_cpus=6, num_tpus=1)

    dep = llm_deployment(
        MODEL,
        max_seq_len=MAX_SEQ,
        new_tokens=NEW_TOKENS,
        max_batch_size=MAX_BATCH,
        batch_wait_timeout_s=0.02,
        num_tpus=1,
        autoscaling_config={
            # engaged: scales on in-flight load, pinned to the one chip
            "min_replicas": 1,
            "max_replicas": 1,
            "target_num_ongoing_requests_per_replica": 32,
        },
    )
    handle = serve.run(dep.bind())
    # warmup: compile the generation program
    t0 = time.time()
    ray_tpu.get(handle.remote(1), timeout=1800)
    compile_s = time.time() - t0
    info = ray_tpu.get(
        serve.get_deployment_handle("llm").method("info").remote(), timeout=60
    )

    loads = [4, 16, 32]
    rows = []
    for concurrency in loads:
        lat: list = []
        t0 = time.time()
        total_requests = concurrency * 4
        done = 0
        inflight = {}
        i = 0
        while done < total_requests:
            while len(inflight) < concurrency and i < total_requests:
                inflight[handle.remote(i)] = time.time()
                i += 1
            ready, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=600)
            for r in ready:
                start = inflight.pop(r)
                toks = ray_tpu.get(r, timeout=60)
                assert len(toks) == NEW_TOKENS
                lat.append(time.time() - start)
                done += 1
        dt = time.time() - t0
        lat_ms = np.asarray(lat) * 1000
        rows.append(
            {
                "offered_concurrency": concurrency,
                "tokens_per_sec": round(total_requests * NEW_TOKENS / dt, 1),
                "requests_per_sec": round(total_requests / dt, 2),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            }
        )

    # TTFT/TPOT from the serve request-trace plane: the replica stamps
    # prefill/first-token/decode boundaries per request (serve/tracing.py),
    # the head joins them next to the task flight records, and the summary
    # reports the percentiles — the baseline the continuous-batching
    # engine (ROADMAP item 1) has to beat.
    ttft = tpot = {}
    try:
        from ray_tpu.experimental.state import summarize_workloads

        serve_summary = summarize_workloads("serve")
        ttft = serve_summary.get("ttft", {}).get("llm") or {}
        tpot = serve_summary.get("tpot", {}).get("llm") or {}
    except Exception as e:  # noqa: BLE001 — bench must still emit a row
        print(f"serve-trace summary unavailable: {e}")

    result = {
        "metric": "serve_llama_decode_tokens_per_sec_per_chip",
        "value": max(r["tokens_per_sec"] for r in rows),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "vs_baseline_basis": "existence (reference publishes no absolute number)",
        "model": MODEL,
        "params_b": info["params_b"],
        "platform": info["platform"],
        "engine": "ShardedLLM tp=%d (donated-cache prefill+decode)" % info["tp"],
        "new_tokens_per_request": NEW_TOKENS,
        "batching": {"max_batch_size": MAX_BATCH, "batch_wait_timeout_s": 0.02},
        "autoscaling_engaged": True,
        "compile_s": round(compile_s, 1),
        "ttft_ms_p50": round(ttft["p50"] * 1e3, 1) if ttft else None,
        "ttft_ms_p99": round(ttft["p99"] * 1e3, 1) if ttft else None,
        "tpot_ms_p50": round(tpot["p50"] * 1e3, 2) if tpot else None,
        "tpot_ms_p99": round(tpot["p99"] * 1e3, 2) if tpot else None,
        "loads": rows,
    }
    with open("SERVE_BENCH_r05.json", "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
