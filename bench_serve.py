"""Serve LLM benchmark (BASELINE config #5 shape): Llama decode on the
TPU behind a @serve.batch deployment — tokens/s + request p50/p99 at
several offered loads, autoscaling engaged.

Product path: client → DeploymentHandle → TPU-claiming replica actor →
ONE jitted lax.scan generating all requested tokens per coalesced batch
(per-token host dispatch would be tunnel-RPC-bound; the scan keeps the
whole generation on-chip).  Model: a llama-family config sized for one
16G v5e chip in bf16 (llama2_7b bf16 weights alone are ~13.5 GB — the
7B-at-scale story is the multi-chip mesh in the dryrun; serving THIS
chip honestly means ~3B).  Reference analog:
python/ray/serve/benchmarks + serve/batching.py:46.

Writes SERVE_BENCH_r04.json and prints one JSON line.
"""

import json
import os
import time

import numpy as np

MAX_SEQ = 256
NEW_TOKENS = 32
# B=8 is the measured sweet spot on one 16G v5e: the in-place cache path
# decodes at 18.6ms/step (429 tok/s raw); B=16's 2x2.6GB cache + 6.7GB
# weights crosses the HBM aliasing cliff and REGRESSES to 84ms/step
MAX_BATCH = 8
MODEL = os.environ.get("SERVE_BENCH_MODEL", "llama_3b")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # driver never claims the chip
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=6, num_tpus=1)

    @serve.deployment(
        name="llm",
        ray_actor_options={"num_tpus": 1},
        max_concurrent_queries=64,
        autoscaling_config={
            # engaged: scales on in-flight load, pinned to the one chip
            "min_replicas": 1,
            "max_replicas": 1,
            "target_num_ongoing_requests_per_replica": 32,
        },
    )
    class LlamaService:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models.llama import LlamaConfig, LlamaModel

            cfg = getattr(LlamaConfig, MODEL)(
                max_seq_len=MAX_SEQ,
                param_dtype=jnp.bfloat16,  # serving: weights live bf16
            )
            self.cfg = cfg
            self.model = LlamaModel(cfg)
            self.params = self.model.init(jax.random.PRNGKey(0))
            self.platform = jax.devices()[0].platform

            def generate(params, tokens0, n_new):
                B = tokens0.shape[0]
                cache = self.model.init_cache(B)

                def body(carry, t):
                    tok, cache = carry
                    logits, cache = self.model.decode_step(params, cache, tok, t)
                    nxt = jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
                    return (nxt, cache), nxt[:, 0]

                (_, _), toks = jax.lax.scan(
                    body, (tokens0, cache), jnp.arange(n_new)
                )
                return toks.T  # [B, n_new]

            import functools

            self._generate = jax.jit(functools.partial(generate, n_new=NEW_TOKENS))

        @serve.batch(max_batch_size=MAX_BATCH, batch_wait_timeout_s=0.02)
        async def generate(self, prompts):
            import jax.numpy as jnp

            B = len(prompts)
            # pad to the ONE compiled batch shape: a ragged batch per
            # coalesce would retrace/recompile per distinct size
            ids = [int(p) % self.cfg.vocab_size for p in prompts]
            ids += [0] * (MAX_BATCH - B)
            tokens0 = jnp.asarray([[i] for i in ids], jnp.int32)
            out = np.asarray(self._generate(self.params, tokens0))
            return [out[b].tolist() for b in range(B)]

        async def __call__(self, prompt):
            return await self.generate(prompt)

        def info(self):
            return {
                "platform": self.platform,
                "params_b": round(self.cfg.num_params() / 1e9, 2),
            }

    handle = serve.run(LlamaService.bind())
    # warmup: compile the generation program
    t0 = time.time()
    ray_tpu.get(handle.remote(1), timeout=1200)
    compile_s = time.time() - t0
    info = ray_tpu.get(
        serve.get_deployment_handle("llm").method("info").remote(), timeout=60
    )

    loads = [4, 16, 32]
    rows = []
    for concurrency in loads:
        lat: list = []
        t0 = time.time()
        total_requests = concurrency * 4
        done = 0
        inflight = {}
        i = 0
        while done < total_requests:
            while len(inflight) < concurrency and i < total_requests:
                inflight[handle.remote(i)] = time.time()
                i += 1
            ready, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=600)
            for r in ready:
                start = inflight.pop(r)
                toks = ray_tpu.get(r, timeout=60)
                assert len(toks) == NEW_TOKENS
                lat.append(time.time() - start)
                done += 1
        dt = time.time() - t0
        lat_ms = np.asarray(lat) * 1000
        rows.append(
            {
                "offered_concurrency": concurrency,
                "tokens_per_sec": round(total_requests * NEW_TOKENS / dt, 1),
                "requests_per_sec": round(total_requests / dt, 2),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 1),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 1),
            }
        )

    result = {
        "metric": "serve_llama_decode_tokens_per_sec_per_chip",
        "value": max(r["tokens_per_sec"] for r in rows),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "model": MODEL,
        "params_b": info["params_b"],
        "platform": info["platform"],
        "new_tokens_per_request": NEW_TOKENS,
        "batching": {"max_batch_size": MAX_BATCH, "batch_wait_timeout_s": 0.02},
        "autoscaling_engaged": True,
        "compile_s": round(compile_s, 1),
        "loads": rows,
    }
    with open("SERVE_BENCH_r04.json", "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
