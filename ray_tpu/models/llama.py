"""Llama family, TPU-first: RMSNorm + RoPE + SwiGLU + grouped-query attn.

The serving-side flagship (BASELINE config #5: Serve Llama-2-7B replica).
Same functional conventions as gpt2.py — pytree params with stacked
[n_layer, ...] leading dim, lax.scan + remat, bf16 compute, declarative
PartitionSpecs — plus an autoregressive KV-cache decode path for Serve
replicas (fixed-shape cache, jit-friendly, batched).

The reference ships no LM; its serve replicas wrap user torch modules
(reference: python/ray/serve/_private/replica.py:58).  Here the model is
first-party so a deployment is jit-compiled end to end.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def padded_vocab(self) -> int:
        return _round_up(self.vocab_size, 128)

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama2_13b(cls, **kw) -> "LlamaConfig":
        return cls(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40, hidden_dim=13824, **kw)

    @classmethod
    def llama_3b(cls, **kw) -> "LlamaConfig":
        """~3.3B llama-family config sized for ONE 16G v5e chip in bf16
        (6.7 GB weights + KV cache headroom; llama2_7b bf16 weights alone
        are ~13.5 GB — 7B serving is a multi-chip mesh story).  head_dim
        128 keeps the attention MXU/lane aligned."""
        return cls(dim=3072, n_layers=26, n_heads=24, n_kv_heads=24, hidden_dim=8192, **kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 64)
        return cls(dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128, **kw)

    def num_params(self) -> int:
        E, L, H = self.dim, self.n_layers, self.hidden_dim
        kv_dim = self.n_kv_heads * self.head_dim
        per_layer = 2 * E * E + 2 * E * kv_dim + 3 * E * H + 2 * E
        return int(self.padded_vocab * E * 2 + L * per_layer + E)


def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt((x32**2).mean(-1, keepdims=True) + eps)
    return norm * scale


def _rope(x, positions, theta):
    # x: [..., seq, heads, head_dim]
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., seq, d/2]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


class LlamaModel:
    def __init__(self, config: LlamaConfig):
        self.config = config

    # -------------------------------------------------------------- params

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        cfg = self.config
        E, L, V, H = cfg.dim, cfg.n_layers, cfg.padded_vocab, cfg.hidden_dim
        kv_dim = cfg.n_kv_heads * cfg.head_dim
        pd = cfg.param_dtype
        k = iter(jax.random.split(rng, 10))
        std = 0.02

        def norm(key, shape, s=std):
            return (jax.random.normal(key, shape) * s).astype(pd)

        return {
            "tok_emb": norm(next(k), (V, E)),
            "out_head": norm(next(k), (E, V)),
            "final_norm": jnp.ones((E,), pd),
            "layers": {
                "attn_norm": jnp.ones((L, E), pd),
                "ffn_norm": jnp.ones((L, E), pd),
                "wq": norm(next(k), (L, E, E)),
                "wk": norm(next(k), (L, E, kv_dim)),
                "wv": norm(next(k), (L, E, kv_dim)),
                "wo": norm(next(k), (L, E, E), std / math.sqrt(2 * L)),
                "w_gate": norm(next(k), (L, E, H)),
                "w_up": norm(next(k), (L, E, H)),
                "w_down": norm(next(k), (L, H, E), std / math.sqrt(2 * L)),
            },
        }

    def param_pspecs(self, mesh=None) -> Dict[str, Any]:
        # mesh accepted for interface parity with GPT2Model (whose pp path
        # re-layers the specs); llama pp integration rides the same pipeline
        # primitive when needed
        return {
            "tok_emb": P("tp", None),
            "out_head": P(None, "tp"),
            "final_norm": P(None),
            "layers": {
                "attn_norm": P("fsdp", None),
                "ffn_norm": P("fsdp", None),
                "wq": P("fsdp", None, "tp"),
                "wk": P("fsdp", None, "tp"),
                "wv": P("fsdp", None, "tp"),
                "wo": P("fsdp", "tp", None),
                "w_gate": P("fsdp", None, "tp"),
                "w_up": P("fsdp", None, "tp"),
                "w_down": P("fsdp", "tp", None),
            },
        }

    # ------------------------------------------------------------- forward

    def _layer(self, x, lp, positions, kv_cache=None, cache_index=None):
        cfg = self.config
        cd = cfg.compute_dtype
        B, S, E = x.shape
        H, KV, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

        h = _rms_norm(x, lp["attn_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        q = (h @ lp["wq"].astype(cd)).reshape(B, S, H, D)
        k = (h @ lp["wk"].astype(cd)).reshape(B, S, KV, D)
        v = (h @ lp["wv"].astype(cd)).reshape(B, S, KV, D)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        new_cache = None
        if kv_cache is not None:
            # decode: the FULL [L, B, max_seq, KV, D] cache rides through —
            # the write is ONE token-sized dynamic_update_slice (25KB), not
            # a rewrite of this layer's whole slice, so XLA keeps the scan
            # carry in place and per-step HBM traffic is reads-only
            # (weights + cache).  Rewriting per-layer slices through a
            # layer-scan's stacked outputs measured 4-5x slower.
            ck_all, cv_all, li = kv_cache
            ck_all = jax.lax.dynamic_update_slice(
                ck_all, k[None].astype(ck_all.dtype), (li, 0, cache_index, 0, 0)
            )
            cv_all = jax.lax.dynamic_update_slice(
                cv_all, v[None].astype(cv_all.dtype), (li, 0, cache_index, 0, 0)
            )
            # li is a static python int (unrolled layer loop)
            k = ck_all[li]
            v = cv_all[li]
            new_cache = (ck_all, cv_all)
            kv_len = k.shape[1]
            kv_pos = jnp.arange(kv_len)
            mask = kv_pos[None, :] <= positions[:, None]  # [S(q), kv_len]
        else:
            mask = jnp.tril(jnp.ones((S, S), bool))

        # grouped-query: repeat kv heads up to H
        if KV != H:
            rep = H // KV
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if kv_cache is None:
            # train/prefill: the shared dispatch (splash pallas kernel on
            # TPU, fused XLA elsewhere — ops/attention.py); decode keeps
            # the masked einsum below (ragged kv lengths don't fit the
            # block kernel)
            from ray_tpu.ops.attention import causal_attention

            attn = causal_attention(q, k, v).reshape(B, S, E)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (D**-0.5)
            scores = jnp.where(mask[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(cd)
            attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, E)
        x = x + attn @ lp["wo"].astype(cd)

        h = _rms_norm(x, lp["ffn_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(cd))
        up = h @ lp["w_up"].astype(cd)
        x = x + (gate * up) @ lp["w_down"].astype(cd)
        return x, new_cache

    def apply(self, params, tokens, mesh=None):
        """Train/prefill forward: tokens [B, S] → logits [B, S, V] (bf16)."""
        cfg = self.config
        cd = cfg.compute_dtype
        B, S = tokens.shape
        x = params["tok_emb"].astype(cd)[tokens]
        positions = jnp.arange(S)

        def body(x, lp):
            if cfg.remat:
                y, _ = jax.checkpoint(
                    lambda x_, lp_: self._layer(x_, lp_, positions)
                )(x, lp)
            else:
                y, _ = self._layer(x, lp, positions)
            return y, None

        x, _ = jax.lax.scan(body, x, params["layers"])
        x = _rms_norm(x, params["final_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        return x @ params["out_head"].astype(cd)

    def loss(self, params, tokens, targets, mesh=None):
        cfg = self.config
        logits = self.apply(params, tokens, mesh).astype(jnp.float32)
        if cfg.padded_vocab != cfg.vocab_size:
            pad_mask = jnp.arange(cfg.padded_vocab) >= cfg.vocab_size
            logits = jnp.where(pad_mask, -1e30, logits)
        label_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        lse = jax.nn.logsumexp(logits, axis=-1)
        return (lse - label_logit).mean()

    # -------------------------------------------------------------- decode

    def init_cache(self, batch: int) -> Tuple:
        """Per-layer fixed-shape KV cache: [L, B, max_seq, KV, D] pair."""
        cfg = self.config
        shape = (cfg.n_layers, batch, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim)
        return (
            jnp.zeros(shape, cfg.compute_dtype),
            jnp.zeros(shape, cfg.compute_dtype),
        )

    def decode_step(self, params, cache, tokens, position: jax.Array):
        """One token per sequence: tokens [B, 1], position scalar index.
        Returns (logits [B, V], new_cache).  jit once, call per token —
        the Serve replica's hot loop."""
        cfg = self.config
        cd = cfg.compute_dtype
        B = tokens.shape[0]
        x = params["tok_emb"].astype(cd)[tokens]  # [B, 1, E]
        positions = jnp.array([position]) if jnp.ndim(position) == 0 else position[None]
        positions = jnp.reshape(positions, (1,))

        ck_all, cv_all = cache
        # python loop over layers (unrolled, static layer index): each
        # layer's cache update is a single token-sized in-place write into
        # the full 5-D cache.  A lax.scan over layers would route the cache
        # through stacked scan OUTPUTS, rewriting all L x [B,S,KV,D] slices
        # every step — measured 63.8ms/step at B=16 vs ~15ms unrolled
        for li in range(cfg.n_layers):
            lp = jax.tree.map(lambda p: p[li], params["layers"])
            x, (ck_all, cv_all) = self._layer(
                x, lp, positions, kv_cache=(ck_all, cv_all, li), cache_index=position
            )
        x = _rms_norm(x, params["final_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        logits = (x @ params["out_head"].astype(cd))[:, 0, :]
        return logits, (ck_all, cv_all)
