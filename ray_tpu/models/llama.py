"""Llama family, TPU-first: RMSNorm + RoPE + SwiGLU + grouped-query attn.

The serving-side flagship (BASELINE config #5: Serve Llama-2-7B replica).
Same functional conventions as gpt2.py — pytree params with stacked
[n_layer, ...] leading dim, lax.scan + remat, bf16 compute, declarative
PartitionSpecs — plus an autoregressive KV-cache decode path for Serve
replicas (fixed-shape cache, jit-friendly, batched).

The reference ships no LM; its serve replicas wrap user torch modules
(reference: python/ray/serve/_private/replica.py:58).  Here the model is
first-party so a deployment is jit-compiled end to end.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def padded_vocab(self) -> int:
        return _round_up(self.vocab_size, 128)

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama2_13b(cls, **kw) -> "LlamaConfig":
        return cls(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40, hidden_dim=13824, **kw)

    @classmethod
    def llama_3b(cls, **kw) -> "LlamaConfig":
        """~3.3B llama-family config sized for ONE 16G v5e chip in bf16
        (6.7 GB weights + KV cache headroom; llama2_7b bf16 weights alone
        are ~13.5 GB — 7B serving is a multi-chip mesh story).  head_dim
        128 keeps the attention MXU/lane aligned."""
        return cls(dim=3072, n_layers=26, n_heads=24, n_kv_heads=24, hidden_dim=8192, **kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 64)
        return cls(dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128, **kw)

    def num_params(self) -> int:
        E, L, H = self.dim, self.n_layers, self.hidden_dim
        kv_dim = self.n_kv_heads * self.head_dim
        per_layer = 2 * E * E + 2 * E * kv_dim + 3 * E * H + 2 * E
        return int(self.padded_vocab * E * 2 + L * per_layer + E)


def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt((x32**2).mean(-1, keepdims=True) + eps)
    return norm * scale


def _rope(x, positions, theta):
    # x: [..., seq, heads, head_dim]
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., seq, d/2]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


class LlamaModel:
    def __init__(self, config: LlamaConfig):
        self.config = config

    # -------------------------------------------------------------- params

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        cfg = self.config
        E, L, V, H = cfg.dim, cfg.n_layers, cfg.padded_vocab, cfg.hidden_dim
        kv_dim = cfg.n_kv_heads * cfg.head_dim
        pd = cfg.param_dtype
        k = iter(jax.random.split(rng, 10))
        std = 0.02

        def norm(key, shape, s=std):
            return (jax.random.normal(key, shape) * s).astype(pd)

        return {
            "tok_emb": norm(next(k), (V, E)),
            "out_head": norm(next(k), (E, V)),
            "final_norm": jnp.ones((E,), pd),
            "layers": {
                "attn_norm": jnp.ones((L, E), pd),
                "ffn_norm": jnp.ones((L, E), pd),
                "wq": norm(next(k), (L, E, E)),
                "wk": norm(next(k), (L, E, kv_dim)),
                "wv": norm(next(k), (L, E, kv_dim)),
                "wo": norm(next(k), (L, E, E), std / math.sqrt(2 * L)),
                "w_gate": norm(next(k), (L, E, H)),
                "w_up": norm(next(k), (L, E, H)),
                "w_down": norm(next(k), (L, H, E), std / math.sqrt(2 * L)),
            },
        }

    def param_pspecs(self, mesh=None) -> Dict[str, Any]:
        # mesh accepted for interface parity with GPT2Model (whose pp path
        # re-layers the specs); llama pp integration rides the same pipeline
        # primitive when needed
        return {
            "tok_emb": P("tp", None),
            "out_head": P(None, "tp"),
            "final_norm": P(None),
            "layers": {
                "attn_norm": P("fsdp", None),
                "ffn_norm": P("fsdp", None),
                "wq": P("fsdp", None, "tp"),
                "wk": P("fsdp", None, "tp"),
                "wv": P("fsdp", None, "tp"),
                "wo": P("fsdp", "tp", None),
                "w_gate": P("fsdp", None, "tp"),
                "w_up": P("fsdp", None, "tp"),
                "w_down": P("fsdp", "tp", None),
            },
        }

    # ------------------------------------------------------------- forward

    def _layer(self, x, lp, positions, kv_cache=None, cache_index=None):
        cfg = self.config
        cd = cfg.compute_dtype
        B, S, E = x.shape
        H, KV, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

        h = _rms_norm(x, lp["attn_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        q = (h @ lp["wq"].astype(cd)).reshape(B, S, H, D)
        k = (h @ lp["wk"].astype(cd)).reshape(B, S, KV, D)
        v = (h @ lp["wv"].astype(cd)).reshape(B, S, KV, D)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        new_cache = None
        if kv_cache is not None:
            # decode: the FULL [L, B, max_seq, KV, D] cache rides through —
            # the write is ONE token-sized dynamic_update_slice (25KB), not
            # a rewrite of this layer's whole slice, so XLA keeps the scan
            # carry in place and per-step HBM traffic is reads-only
            # (weights + cache).  Rewriting per-layer slices through a
            # layer-scan's stacked outputs measured 4-5x slower.
            ck_all, cv_all, li = kv_cache
            ck_all = jax.lax.dynamic_update_slice(
                ck_all, k[None].astype(ck_all.dtype), (li, 0, cache_index, 0, 0)
            )
            cv_all = jax.lax.dynamic_update_slice(
                cv_all, v[None].astype(cv_all.dtype), (li, 0, cache_index, 0, 0)
            )
            # li is a static python int (unrolled layer loop)
            k = ck_all[li]
            v = cv_all[li]
            new_cache = (ck_all, cv_all)
            kv_len = k.shape[1]
            kv_pos = jnp.arange(kv_len)
            mask = kv_pos[None, :] <= positions[:, None]  # [S(q), kv_len]
        else:
            mask = jnp.tril(jnp.ones((S, S), bool))

        # grouped-query: repeat kv heads up to H
        if KV != H:
            rep = H // KV
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if kv_cache is None:
            # train/prefill: the shared dispatch (splash pallas kernel on
            # TPU, fused XLA elsewhere — ops/attention.py); decode keeps
            # the masked einsum below (ragged kv lengths don't fit the
            # block kernel)
            from ray_tpu.ops.attention import causal_attention

            attn = causal_attention(q, k, v).reshape(B, S, E)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (D**-0.5)
            scores = jnp.where(mask[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(cd)
            attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, E)
        x = x + attn @ lp["wo"].astype(cd)

        h = _rms_norm(x, lp["ffn_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(cd))
        up = h @ lp["w_up"].astype(cd)
        x = x + (gate * up) @ lp["w_down"].astype(cd)
        return x, new_cache

    def apply(self, params, tokens, mesh=None):
        """Train/prefill forward: tokens [B, S] → logits [B, S, V] (bf16)."""
        cfg = self.config
        cd = cfg.compute_dtype
        B, S = tokens.shape
        x = params["tok_emb"].astype(cd)[tokens]
        positions = jnp.arange(S)

        def body(x, lp):
            if cfg.remat:
                y, _ = jax.checkpoint(
                    lambda x_, lp_: self._layer(x_, lp_, positions)
                )(x, lp)
            else:
                y, _ = self._layer(x, lp, positions)
            return y, None

        x, _ = jax.lax.scan(body, x, params["layers"])
        x = _rms_norm(x, params["final_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        return x @ params["out_head"].astype(cd)

    def loss(self, params, tokens, targets, mesh=None):
        cfg = self.config
        logits = self.apply(params, tokens, mesh).astype(jnp.float32)
        if cfg.padded_vocab != cfg.vocab_size:
            pad_mask = jnp.arange(cfg.padded_vocab) >= cfg.vocab_size
            logits = jnp.where(pad_mask, -1e30, logits)
        label_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        lse = jax.nn.logsumexp(logits, axis=-1)
        return (lse - label_logit).mean()

    # -------------------------------------------------------------- decode

    def init_cache(self, batch: int) -> Tuple:
        """Per-layer fixed-shape KV cache: [L, B, max_seq, KV, D] pair."""
        cfg = self.config
        shape = (cfg.n_layers, batch, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim)
        return (
            jnp.zeros(shape, cfg.compute_dtype),
            jnp.zeros(shape, cfg.compute_dtype),
        )

    # ------------------------------------------------- paged decode (engine)
    #
    # The continuous-batching engine (ray_tpu/serve/engine/) shares ONE
    # fixed-shape page pool across sequences of different lengths: physical
    # KV pages [L, num_pages, page_size, KV, D] plus a per-slot page table
    # mapping logical page -> physical page (-1 = unallocated).  Shapes
    # depend only on (num_slots, pages_per_slot, page_size), never on any
    # sequence's length — the jit-shape invariant that keeps a mixed-length
    # fleet on one compiled program (engine/DESIGN.md).  This is the
    # gather-based reference formulation of paged attention (layout follows
    # the TPU paged-attention kernel: k_pages/v_pages pools + page_indices +
    # lengths); a production TPU build swaps the gather for the pallas
    # paged-attention kernel with per-page async DMA — the pool layout and
    # page tables are already kernel-shaped.

    def _paged_write(self, buf, li: int, wpage, woff, vals):
        """Scatter one token per slot into layer ``li`` of a page pool.
        ``wpage`` rows for inactive/unallocated slots are out of range and
        dropped — token-sized update on the full buffer, same in-place
        contract as decode_step's dynamic_update_slice."""
        return buf.at[li, wpage, woff].set(vals.astype(buf.dtype), mode="drop")

    def _paged_context(self, buf, li: int, gpage, goff):
        """Gather a slot's logical context [*, T, KV, D] from layer ``li``
        of the pool (clipped indices; invalid rows are masked by the
        caller's valid_ctx, never read as attention inputs)."""
        return buf[li, gpage, goff]

    def _paged_attend(self, q, keys, vals, valid_ctx):
        """Masked single-direction attention over gathered paged context.
        q [B, S, H, D]; keys/vals [B, T, KV, D]; valid_ctx [B, S, T]."""
        cfg = self.config
        H, KV, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        if KV != H:
            rep = H // KV
            keys = jnp.repeat(keys, rep, axis=2)
            vals = jnp.repeat(vals, rep, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, keys).astype(jnp.float32) * (
            D**-0.5
        )
        scores = jnp.where(valid_ctx[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.compute_dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, vals)

    def _paged_layer(self, x, lp, li, positions, pages, wpage, woff, gpage, goff, valid_ctx):
        """One transformer layer over paged KV: write this step's K/V into
        the pool, gather each slot's logical context, attend.  x [B, S, E]
        (decode: B=slots,S=1; prefill chunk: B=1,S=chunk)."""
        cfg = self.config
        cd = cfg.compute_dtype
        B, S, E = x.shape
        H, KV, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        kp, vp = pages

        h = _rms_norm(x, lp["attn_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        q = (h @ lp["wq"].astype(cd)).reshape(B, S, H, D)
        k = (h @ lp["wk"].astype(cd)).reshape(B, S, KV, D)
        v = (h @ lp["wv"].astype(cd)).reshape(B, S, KV, D)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        kp = self._paged_write(kp, li, wpage, woff, k.reshape(-1, KV, D))
        vp = self._paged_write(vp, li, wpage, woff, v.reshape(-1, KV, D))
        keys = self._paged_context(kp, li, gpage, goff)
        vals = self._paged_context(vp, li, gpage, goff)
        if keys.ndim == 3:  # single-slot prefill: add the batch dim
            keys, vals = keys[None], vals[None]
        attn = self._paged_attend(q, keys, vals, valid_ctx).reshape(B, S, E)
        x = x + attn @ lp["wo"].astype(cd)

        h = _rms_norm(x, lp["ffn_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(cd))
        up = h @ lp["w_up"].astype(cd)
        x = x + (gate * up) @ lp["w_down"].astype(cd)
        return x, (kp, vp)

    def _sample_greedy(self, logits):
        """argmax with the vocab padding masked (a padded id must never
        enter a sequence — it has no embedding semantics)."""
        cfg = self.config
        if cfg.padded_vocab != cfg.vocab_size:
            pad = jnp.arange(cfg.padded_vocab) >= cfg.vocab_size
            logits = jnp.where(pad, -jnp.inf, logits)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def init_pages(self, num_pages: int, page_size: int) -> Tuple:
        """Physical KV page pool shared by every engine slot:
        [L, num_pages, page_size, KV, D] pair."""
        cfg = self.config
        shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
        return (
            jnp.zeros(shape, cfg.compute_dtype),
            jnp.zeros(shape, cfg.compute_dtype),
        )

    def decode_step_paged(
        self, params, pages, tables, tokens, positions, active, page_size: int
    ):
        """One engine iteration: decode one token for every active slot.

        pages: (k_pages, v_pages) [L, NP, PS, KV, D]; tables [S, MP] int32
        (physical page per logical page, -1 unallocated); tokens [S] int32
        (the token each slot feeds); positions [S] int32 (cache index the
        fed token is written at); active [S] bool.  Returns
        (next_tokens [S] int32 — greedy, device-argmaxed so only S ints
        cross to the host per step — and the updated pool)."""
        cfg = self.config
        cd = cfg.compute_dtype
        S, MP = tables.shape
        NP = pages[0].shape[1]
        T = MP * page_size

        x = params["tok_emb"].astype(cd)[tokens][:, None, :]  # [S, 1, E]
        pos2 = positions[:, None]  # [S, 1]: per-slot rope positions
        # write target: one pool row per slot; inactive or table-miss rows
        # go out of range and are dropped by the scatter
        wpage = jnp.take_along_axis(tables, (positions // page_size)[:, None], axis=1)[:, 0]
        wpage = jnp.where(active & (wpage >= 0), wpage, NP)
        woff = positions % page_size
        # gather map: logical context index j -> (physical page, offset)
        j = jnp.arange(T)
        gpage = tables[:, j // page_size]  # [S, T]
        goff = jnp.broadcast_to(j % page_size, (S, T))
        valid_ctx = (gpage >= 0) & (j[None, :] <= positions[:, None])
        valid_ctx = valid_ctx & active[:, None]
        gpage = jnp.clip(gpage, 0, NP - 1)
        valid_ctx = valid_ctx[:, None, :]  # [S, 1(q), T]

        for li in range(cfg.n_layers):
            lp = jax.tree.map(lambda p: p[li], params["layers"])
            x, pages = self._paged_layer(
                x, lp, li, pos2, pages, wpage, woff, gpage, goff, valid_ctx
            )
        x = _rms_norm(x, params["final_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        logits = (x @ params["out_head"].astype(cd))[:, 0, :]
        return self._sample_greedy(logits), pages

    def prefill_chunk_paged(
        self, params, pages, table_row, tokens, start_pos, n_valid, page_size: int
    ):
        """One chunk of one slot's prompt: write positions
        start_pos..start_pos+n_valid-1 into the pool and return the greedy
        next token after the chunk's LAST valid position (meaningful only
        on the final chunk — the request's first generated token).

        tokens [C] int32 (tail chunks are padded; padding masked by
        n_valid); table_row [MP] int32; start_pos / n_valid scalars.  The
        chunk length C is static, so a prompt of any length runs as
        ceil(P/C) calls of ONE compiled program — chunked prefill never
        adds a shape, and in-flight decode streams wait at most one chunk
        (engine/DESIGN.md)."""
        cfg = self.config
        cd = cfg.compute_dtype
        C = tokens.shape[0]
        (MP,) = table_row.shape
        NP = pages[0].shape[1]
        T = MP * page_size

        pos = start_pos + jnp.arange(C)  # [C]
        valid_q = jnp.arange(C) < n_valid
        x = params["tok_emb"].astype(cd)[tokens][None]  # [1, C, E]
        wpage = table_row[pos // page_size]
        wpage = jnp.where(valid_q & (wpage >= 0), wpage, NP)
        woff = pos % page_size
        j = jnp.arange(T)
        gpage = table_row[j // page_size]  # [T]
        goff = j % page_size
        # causal over the slot's logical context, chunk included (K/V land
        # in the pool before the gather)
        valid_ctx = (gpage[None, :] >= 0) & (j[None, :] <= pos[:, None])
        valid_ctx = valid_ctx & valid_q[:, None]
        gpage = jnp.clip(gpage, 0, NP - 1)
        valid_ctx = valid_ctx[None]  # [1, C, T]

        for li in range(cfg.n_layers):
            lp = jax.tree.map(lambda p: p[li], params["layers"])
            x, pages = self._paged_layer(
                x, lp, li, pos[None, :], pages, wpage, woff, gpage, goff, valid_ctx
            )
        x = _rms_norm(x, params["final_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        logits = (x[0] @ params["out_head"].astype(cd))  # [C, V]
        last = jnp.clip(n_valid - 1, 0, C - 1)
        return self._sample_greedy(logits[last]), pages

    def decode_step(self, params, cache, tokens, position: jax.Array):
        """One token per sequence: tokens [B, 1], position scalar index.
        Returns (logits [B, V], new_cache).  jit once, call per token —
        the Serve replica's hot loop."""
        cfg = self.config
        cd = cfg.compute_dtype
        B = tokens.shape[0]
        x = params["tok_emb"].astype(cd)[tokens]  # [B, 1, E]
        positions = jnp.array([position]) if jnp.ndim(position) == 0 else position[None]
        positions = jnp.reshape(positions, (1,))

        ck_all, cv_all = cache
        # python loop over layers (unrolled, static layer index): each
        # layer's cache update is a single token-sized in-place write into
        # the full 5-D cache.  A lax.scan over layers would route the cache
        # through stacked scan OUTPUTS, rewriting all L x [B,S,KV,D] slices
        # every step — measured 63.8ms/step at B=16 vs ~15ms unrolled
        for li in range(cfg.n_layers):
            lp = jax.tree.map(lambda p: p[li], params["layers"])
            x, (ck_all, cv_all) = self._layer(
                x, lp, positions, kv_cache=(ck_all, cv_all, li), cache_index=position
            )
        x = _rms_norm(x, params["final_norm"].astype(jnp.float32), cfg.norm_eps).astype(cd)
        logits = (x @ params["out_head"].astype(cd))[:, 0, :]
        return logits, (ck_all, cv_all)
