from ray_tpu.models.gpt2 import GPT2Config, GPT2Model  # noqa: F401
