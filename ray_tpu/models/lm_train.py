"""Sharded LM training step: the compute core under Train's JaxTrainer.

Builds a pjit-compiled (init, step) pair for a GPT2Model over an arbitrary
Mesh.  Replaces the reference's torch DDP/FSDP wrap + NCCL allreduce
(reference: python/ray/train/torch/train_loop_utils.py:56 prepare_model,
config.py:69 _setup_torch_process_group): here the mesh sharding IS the
strategy — dp replicates params and psums grads, fsdp shards params and
optimizer state (ZeRO-style), tp shards within layers — all collectives
inserted by XLA over ICI.

Optimizer-state sharding (ZeRO-1, BASELINE config #4) falls out of the
same spec tree: mu/nu inherit each param's PartitionSpec, so any param
sharded over `fsdp` has its Adam moments sharded identically.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.gpt2 import GPT2Config, GPT2Model
from ray_tpu.parallel.mesh import data_pspec


def _tree_specs_for_opt_state(opt, params, param_specs, mesh=None):
    """PartitionSpec tree for the optimizer state: moment tensors inherit
    their param's spec (path-suffix match), scalars replicate.

    ZeRO-1 completion: when the mesh carries an fsdp axis, moments whose
    param is NOT fsdp-sharded (embeddings, final layernorm) still get a
    shard — Adam's elementwise math lets the moments live sharded while
    the param replicates; XLA all-gathers the sharded update before
    apply.  This is exactly the reference FSDP/ZeRO-1 optimizer-state
    memory split (torch train_loop_utils.py:29-31) without touching the
    forward's tuned layouts."""
    from jax.tree_util import tree_flatten_with_path, tree_map_with_path

    flat, _ = tree_flatten_with_path(param_specs)
    by_path = {tuple(str(k) for k in path): spec for path, spec in flat}
    shapes = jax.eval_shape(opt.init, params)
    fsdp_n = 0
    if mesh is not None and "fsdp" in mesh.axis_names:
        fsdp_n = mesh.shape["fsdp"]

    def leaf_spec(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        pstr = tuple(str(k) for k in path)
        spec = P()
        for start in range(len(pstr)):
            if pstr[start:] in by_path:
                spec = by_path[pstr[start:]]
                break
        if fsdp_n > 1 and all(a is None for a in spec):
            # fully-replicated moment: shard the first fsdp-divisible dim
            for d, size in enumerate(leaf.shape):
                if size % fsdp_n == 0:
                    return P(*([None] * d), "fsdp")
        return spec

    return tree_map_with_path(leaf_spec, shapes)


class TrainStepBundle(NamedTuple):
    init: Any  # (rng) -> (params, opt_state)
    step: Any  # (params, opt_state, tokens, targets) -> (params, opt_state, metrics)
    mesh: Any
    param_shardings: Any
    opt_shardings: Any
    batch_sharding: Any


def make_train_step(
    model: GPT2Model,
    mesh: Mesh,
    *,
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    optimizer=None,
) -> TrainStepBundle:
    import optax

    cfg = model.config
    if optimizer is None:
        optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay),
        )

    param_specs = model.param_pspecs(mesh)
    # drop axes the mesh doesn't carry (e.g. running a tp-annotated model on
    # a pure-dp mesh)
    present = set(mesh.axis_names)

    def _filter(spec):
        if not isinstance(spec, P):
            return spec
        cleaned = tuple(
            (a if (a in present and mesh.shape[a] > 1) else None)
            if not isinstance(a, tuple)
            else tuple(x for x in a if x in present and mesh.shape[x] > 1) or None
            for a in spec
        )
        return P(*cleaned)

    param_specs = jax.tree.map(_filter, param_specs, is_leaf=lambda x: isinstance(x, P))

    def shard(spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree, is_leaf=lambda x: isinstance(x, P)
        )

    param_shardings = shard(param_specs)
    batch_spec = data_pspec(mesh)
    batch_sharding = NamedSharding(mesh, batch_spec)

    dummy = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    opt_specs = _tree_specs_for_opt_state(optimizer, dummy, param_specs, mesh)
    opt_shardings = shard(opt_specs)

    @functools.partial(jax.jit, out_shardings=(param_shardings, opt_shardings))
    def init(rng):
        params = model.init(rng)
        return params, optimizer.init(params)

    def loss_fn(params, tokens, targets):
        return model.loss(params, tokens, targets, mesh)

    use_1f1b = (
        dict(mesh.shape).get("pp", 1) > 1
        and getattr(cfg, "pp_schedule", "gpipe") == "1f1b"
    )

    @functools.partial(
        jax.jit,
        in_shardings=(param_shardings, opt_shardings, batch_sharding, batch_sharding),
        out_shardings=(param_shardings, opt_shardings, None),
        donate_argnums=(0, 1),
    )
    def step(params, opt_state, tokens, targets):
        if use_1f1b:
            # explicit per-microbatch backward (activation memory bounded
            # by pipe depth); grads arrive from inside the schedule
            loss, grads = model.loss_and_grads_1f1b(params, tokens, targets, mesh)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    return TrainStepBundle(init, step, mesh, param_shardings, opt_shardings, batch_sharding)


def synthetic_batch(rng: jax.Array, batch: int, seq: int, vocab: int):
    """Deterministic synthetic LM batch (benchmarks; reference analog:
    release/air_tests synthetic datasets)."""
    tokens = jax.random.randint(rng, (batch, seq + 1), 0, vocab, dtype=jnp.int32)
    return tokens[:, :-1], tokens[:, 1:]


# ---------------------------------------------------------------- step spec


def _lm_build(config, rank, world):
    """Worker-side build for the LM TrainStepSpec: model + jitted grad fn
    + optimizer, params device-resident from here on.  Same init seed on
    every rank (the DP contract test_train.py's eager loops use)."""
    import optax

    cfg = getattr(GPT2Config, config["model"])(compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(int(config["init_seed"])))
    opt = optax.adam(float(config["lr"]))
    opt_state = opt.init(params)
    grad_fn = jax.jit(jax.value_and_grad(lambda p, t, g: model.loss(p, t, g)))
    return {
        "cfg": cfg,
        "params": params,
        "opt": opt,
        "opt_state": opt_state,
        "grad_fn": grad_fn,
        "rank": rank,
        "world": world,
        "batch": int(config["batch"]),
        "seq": int(config["seq"]),
        "sync_grads": bool(config["sync_grads"]),
        "data_seed": int(config["data_seed"]),
        "group": str(config.get("collective_group", "_train_dp")),
    }


def _lm_data(state, idx):
    """Deterministic in (rank, step_idx): checkpoint-resume replays the
    exact stream, which is what makes resumed weights bit-identical."""
    key = jax.random.PRNGKey(state["data_seed"] + idx * 1000 + state["rank"])
    return synthetic_batch(
        key, state["batch"], state["seq"], state["cfg"].vocab_size
    )


def _lm_step(state, batch):
    import optax

    tokens, targets = batch
    loss, grads = state["grad_fn"](state["params"], tokens, targets)
    if state["world"] > 1 and state["sync_grads"]:
        from ray_tpu.train.jax.train_loop_utils import all_reduce_pytree

        grads = all_reduce_pytree(grads, state["world"], group_name=state["group"])
    updates, state["opt_state"] = state["opt"].update(grads, state["opt_state"])
    state["params"] = optax.apply_updates(state["params"], updates)
    return {"loss": loss}


def _lm_fold(state, metrics):
    return {"loss": float(metrics["loss"])}


def _lm_snapshot(state):
    import numpy as np

    return jax.tree.map(
        lambda x: np.asarray(x),
        {"params": state["params"], "opt_state": state["opt_state"]},
    )


def _lm_restore(state, snap):
    state["params"] = jax.tree.map(jnp.asarray, snap["params"])
    state["opt_state"] = jax.tree.map(jnp.asarray, snap["opt_state"])


def make_lm_step_spec(
    model: str = "tiny",
    *,
    batch: int = 4,
    seq: Optional[int] = None,
    steps: int = 10,
    learning_rate: float = 1e-2,
    checkpoint_every: int = 0,
    sync_grads: bool = True,
    init_seed: int = 0,
    data_seed: int = 1,
    collective_group: str = "_train_dp",
    name: str = "lm_train_dag",
):
    """A GPT-2 training run as a ``TrainStepSpec`` (train/jax/step_dag.py):
    the SAME stage functions drive both the eager per-step path and the
    gang-scheduled resident DAG, so eager-vs-dag weight equality is a
    property of the system, not the workload.  Used by the bench.py
    dispatch-overhead pair, the multichip dryrun's gang phase, and
    tests/test_train_dag.py."""
    from ray_tpu.train.jax.step_dag import TrainStepSpec

    cfg = getattr(GPT2Config, model)()
    seq = seq or cfg.block_size
    return TrainStepSpec(
        build=_lm_build,
        data=_lm_data,
        step=_lm_step,
        fold=_lm_fold,
        snapshot=_lm_snapshot,
        restore=_lm_restore,
        steps=steps,
        checkpoint_every=checkpoint_every,
        config={
            "model": model,
            "batch": batch,
            "seq": seq,
            "lr": learning_rate,
            "sync_grads": sync_grads,
            "init_seed": init_seed,
            "data_seed": data_seed,
            # must match JaxConfig.group_name (default TRAIN_GROUP): the
            # step stage reduces on this group, the backend creates it
            "collective_group": collective_group,
        },
        name=name,
        flops_per_step=cfg.flops_per_token() * batch * seq,
    )
