"""Sharded LM training step: the compute core under Train's JaxTrainer.

Builds a pjit-compiled (init, step) pair for a GPT2Model over an arbitrary
Mesh.  Replaces the reference's torch DDP/FSDP wrap + NCCL allreduce
(reference: python/ray/train/torch/train_loop_utils.py:56 prepare_model,
config.py:69 _setup_torch_process_group): here the mesh sharding IS the
strategy — dp replicates params and psums grads, fsdp shards params and
optimizer state (ZeRO-style), tp shards within layers — all collectives
inserted by XLA over ICI.

Optimizer-state sharding (ZeRO-1, BASELINE config #4) falls out of the
same spec tree: mu/nu inherit each param's PartitionSpec, so any param
sharded over `fsdp` has its Adam moments sharded identically.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.gpt2 import GPT2Config, GPT2Model
from ray_tpu.parallel.mesh import data_pspec


def _tree_specs_for_opt_state(opt, params, param_specs, mesh=None):
    """PartitionSpec tree for the optimizer state: moment tensors inherit
    their param's spec (path-suffix match), scalars replicate.

    ZeRO-1 completion: when the mesh carries an fsdp axis, moments whose
    param is NOT fsdp-sharded (embeddings, final layernorm) still get a
    shard — Adam's elementwise math lets the moments live sharded while
    the param replicates; XLA all-gathers the sharded update before
    apply.  This is exactly the reference FSDP/ZeRO-1 optimizer-state
    memory split (torch train_loop_utils.py:29-31) without touching the
    forward's tuned layouts."""
    from jax.tree_util import tree_flatten_with_path, tree_map_with_path

    flat, _ = tree_flatten_with_path(param_specs)
    by_path = {tuple(str(k) for k in path): spec for path, spec in flat}
    shapes = jax.eval_shape(opt.init, params)
    fsdp_n = 0
    if mesh is not None and "fsdp" in mesh.axis_names:
        fsdp_n = mesh.shape["fsdp"]

    def leaf_spec(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        pstr = tuple(str(k) for k in path)
        spec = P()
        for start in range(len(pstr)):
            if pstr[start:] in by_path:
                spec = by_path[pstr[start:]]
                break
        if fsdp_n > 1 and all(a is None for a in spec):
            # fully-replicated moment: shard the first fsdp-divisible dim
            for d, size in enumerate(leaf.shape):
                if size % fsdp_n == 0:
                    return P(*([None] * d), "fsdp")
        return spec

    return tree_map_with_path(leaf_spec, shapes)


class TrainStepBundle(NamedTuple):
    init: Any  # (rng) -> (params, opt_state)
    step: Any  # (params, opt_state, tokens, targets) -> (params, opt_state, metrics)
    mesh: Any
    param_shardings: Any
    opt_shardings: Any
    batch_sharding: Any


def make_train_step(
    model: GPT2Model,
    mesh: Mesh,
    *,
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    optimizer=None,
) -> TrainStepBundle:
    import optax

    cfg = model.config
    if optimizer is None:
        optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay),
        )

    param_specs = model.param_pspecs(mesh)
    # drop axes the mesh doesn't carry (e.g. running a tp-annotated model on
    # a pure-dp mesh)
    present = set(mesh.axis_names)

    def _filter(spec):
        if not isinstance(spec, P):
            return spec
        cleaned = tuple(
            (a if (a in present and mesh.shape[a] > 1) else None)
            if not isinstance(a, tuple)
            else tuple(x for x in a if x in present and mesh.shape[x] > 1) or None
            for a in spec
        )
        return P(*cleaned)

    param_specs = jax.tree.map(_filter, param_specs, is_leaf=lambda x: isinstance(x, P))

    def shard(spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree, is_leaf=lambda x: isinstance(x, P)
        )

    param_shardings = shard(param_specs)
    batch_spec = data_pspec(mesh)
    batch_sharding = NamedSharding(mesh, batch_spec)

    dummy = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    opt_specs = _tree_specs_for_opt_state(optimizer, dummy, param_specs, mesh)
    opt_shardings = shard(opt_specs)

    @functools.partial(jax.jit, out_shardings=(param_shardings, opt_shardings))
    def init(rng):
        params = model.init(rng)
        return params, optimizer.init(params)

    def loss_fn(params, tokens, targets):
        return model.loss(params, tokens, targets, mesh)

    use_1f1b = (
        dict(mesh.shape).get("pp", 1) > 1
        and getattr(cfg, "pp_schedule", "gpipe") == "1f1b"
    )

    @functools.partial(
        jax.jit,
        in_shardings=(param_shardings, opt_shardings, batch_sharding, batch_sharding),
        out_shardings=(param_shardings, opt_shardings, None),
        donate_argnums=(0, 1),
    )
    def step(params, opt_state, tokens, targets):
        if use_1f1b:
            # explicit per-microbatch backward (activation memory bounded
            # by pipe depth); grads arrive from inside the schedule
            loss, grads = model.loss_and_grads_1f1b(params, tokens, targets, mesh)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    return TrainStepBundle(init, step, mesh, param_shardings, opt_shardings, batch_sharding)


def synthetic_batch(rng: jax.Array, batch: int, seq: int, vocab: int):
    """Deterministic synthetic LM batch (benchmarks; reference analog:
    release/air_tests synthetic datasets)."""
    tokens = jax.random.randint(rng, (batch, seq + 1), 0, vocab, dtype=jnp.int32)
    return tokens[:, :-1], tokens[:, 1:]
