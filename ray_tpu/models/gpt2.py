"""GPT-2 family, TPU-first.

The flagship model for the Train stack (BASELINE configs #2 and #4: 124M
data-parallel, 1.5B with ZeRO-1).  Design choices are MXU/HBM-driven, not a
port of any torch modeling code:

- params are a plain pytree with a *stacked* [n_layer, ...] leading dim and
  the forward is one `lax.scan` over layers → one compiled layer body,
  `jax.checkpoint` per layer for rematerialization (HBM ⇄ FLOPs trade).
- compute in bfloat16 (MXU native), master params float32, loss/softmax in
  float32; vocab padded to a multiple of 128 so the logits matmul tiles
  cleanly onto the 128×128 systolic array.
- sharding is declared, not wired: `param_pspecs()` returns a PartitionSpec
  pytree over the standard mesh axes (tp shards attention heads / mlp
  hidden / vocab; fsdp shards the stacked layer dim; dp replicates), so the
  same model runs single-chip or on any Mesh via pjit with no code change.
- sequence parallelism: pass `mesh_axis_sp` to route attention through
  ring_attention (sequence sharded over the `sp` axis).

Reference surface parity: the reference ships no LM of its own — its Train
layer wraps user torch modules (reference: python/ray/train/torch/
train_loop_utils.py prepare_model).  This model is the `train_loop` payload
for our equivalents of the AIR GPT-2 release benchmarks
(reference: release/air_tests/air_benchmarks/).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    block_size: int = 1024
    dropout: float = 0.0  # benchmarks run dropout-free (jit-friendly default)
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "full" recomputes everything; "dots" saves matmul outputs and only
    # recomputes elementwise ops; "lite" saves everything EXCEPT the
    # layernorm/gelu outputs (the cheapest recomputes with the biggest
    # buffers) — the least-recompute policy that still fits a v5e chip at
    # batch 16 with the splash attention kernel
    remat_policy: str = "dots"
    # "auto": pallas flash kernel on TPU, xla einsum elsewhere
    attention_impl: str = "auto"
    # what the QK^T matmul writes: f32 (safe) or bf16 (half the [S,S] HBM
    # traffic; softmax still accumulates f32)
    attn_scores_dtype: Any = jnp.float32
    use_ring_attention: bool = False
    # "fused": chunked linear-head CE that never materializes [B,S,V] logits
    # (ops/cross_entropy.py); "naive": full-logits path; "auto" picks fused
    # unless the sequence axis is sharded (sp ring attention), whose layout
    # the chunked scan would break
    loss_impl: str = "auto"
    # sequence-chunk length per fused-CE scan step; the transient logits
    # block is [B, loss_chunk, padded_vocab] f32.  0 = auto: ~4k tokens
    # per block (bigger blocks amortize scan overhead, measured +0.4 MFU
    # at b16; capped so large batches don't blow the transient)
    loss_chunk: int = 0
    # GPipe microbatches per data shard when the mesh carries a pp axis
    # (bubble fraction (pp-1)/(M+pp-1))
    pp_microbatches: int = 4
    # "gpipe": all-forward-then-autodiff-backward (activations for every
    # in-flight microbatch live across the schedule); "1f1b": explicit
    # per-microbatch backward with a min(M, 2pp-1)-deep activation ring —
    # same gradients, O(pp) activation memory, so M can grow at a fixed
    # budget and shrink the bubble (parallel/pipeline.py 1F1B notes)
    pp_schedule: str = "gpipe"
    # >0 turns every MLP into a top-1 switch MoE with this many experts
    # (parallel/moe.py); experts shard over the ep mesh axis
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25

    @property
    def padded_vocab(self) -> int:
        # 128-lane tiling for the MXU; 50257 → 50304
        return _round_up(self.vocab_size, 128)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @classmethod
    def gpt2_124m(cls, **kw) -> "GPT2Config":
        return cls(n_layer=12, n_head=12, n_embd=768, **kw)

    @classmethod
    def gpt2_350m(cls, **kw) -> "GPT2Config":
        return cls(n_layer=24, n_head=16, n_embd=1024, **kw)

    @classmethod
    def gpt2_774m(cls, **kw) -> "GPT2Config":
        return cls(n_layer=36, n_head=20, n_embd=1280, **kw)

    @classmethod
    def gpt2_1p5b(cls, **kw) -> "GPT2Config":
        return cls(n_layer=48, n_head=25, n_embd=1600, **kw)

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        """CPU-testable toy (virtual-mesh tests, dryruns)."""
        kw.setdefault("vocab_size", 512)
        kw.setdefault("block_size", 64)
        kw.setdefault("n_layer", 2)
        kw.setdefault("n_head", 2)
        kw.setdefault("n_embd", 64)
        return cls(**kw)

    def num_params(self) -> int:
        V, L, E = self.padded_vocab, self.n_layer, self.n_embd
        per_layer = 12 * E * E + 13 * E  # qkv+proj+mlp(4x) + biases + 2 ln
        return V * E + self.block_size * E + L * per_layer + 2 * E

    def flops_per_token(self) -> float:
        """Training FLOPs/token = 6N + 12·L·E·S (PaLM appendix / nanoGPT
        convention): N is total params — wte is tied, used as both input
        embedding and the logits head matmul — plus the attention
        score/value matmuls.  This is the MFU numerator per token."""
        N = self.num_params()
        attn = 12 * self.n_layer * self.n_embd * self.block_size
        return 6.0 * N + attn


class GPT2Model:
    """Functional model: params are an explicit pytree; every method is
    jit/pjit-friendly (no hidden state)."""

    def __init__(self, config: GPT2Config):
        self.config = config

    # ------------------------------------------------------------ params

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        cfg = self.config
        E, L, V, S = cfg.n_embd, cfg.n_layer, cfg.padded_vocab, cfg.block_size
        H = cfg.n_head
        k = iter(jax.random.split(rng, 16))
        std = 0.02
        proj_std = std / math.sqrt(2 * L)  # GPT-2 residual-stream scaling
        pd = cfg.param_dtype

        def norm(key, shape, s):
            return (jax.random.normal(key, shape) * s).astype(pd)

        params = {
            "wte": norm(next(k), (V, E), std),
            "wpe": norm(next(k), (S, E), std),
            "ln_f": {"scale": jnp.ones((E,), pd), "bias": jnp.zeros((E,), pd)},
            "layers": {
                "ln1_scale": jnp.ones((L, E), pd),
                "ln1_bias": jnp.zeros((L, E), pd),
                "ln2_scale": jnp.ones((L, E), pd),
                "ln2_bias": jnp.zeros((L, E), pd),
                "qkv_w": norm(next(k), (L, E, 3 * E), std),
                "qkv_b": jnp.zeros((L, 3 * E), pd),
                "proj_w": norm(next(k), (L, E, E), proj_std),
                "proj_b": jnp.zeros((L, E), pd),
            },
        }
        if cfg.moe_experts:
            X = cfg.moe_experts
            params["layers"].update(
                {
                    "router_w": norm(next(k), (L, E, X), std),
                    "expert_in": norm(next(k), (L, X, E, 4 * E), std),
                    "expert_out": norm(next(k), (L, X, 4 * E, E), proj_std),
                }
            )
        else:
            params["layers"].update(
                {
                    "mlp_in_w": norm(next(k), (L, E, 4 * E), std),
                    "mlp_in_b": jnp.zeros((L, 4 * E), pd),
                    "mlp_out_w": norm(next(k), (L, 4 * E, E), proj_std),
                    "mlp_out_b": jnp.zeros((L, E), pd),
                }
            )
        return params

    def param_pspecs(self, mesh=None) -> Dict[str, Any]:
        """PartitionSpecs over the standard mesh axes.  tp shards the
        contraction-free dim of each matmul (megatron column/row split);
        fsdp shards the stacked layer dim (ZeRO-3-style param sharding —
        all-gather per layer inside scan); embeddings shard vocab on tp.

        On a pp mesh the stacked layer dim is the *stage* dim: sharded over
        pp (one contiguous slice of layers per stage, consumed by the GPipe
        shard_map in backbone).  pp composes with dp/fsdp batch sharding
        AND with tp: the pipeline shard_map is manual over pp/dp/fsdp only,
        so tp-sharded layer weights keep compiler-managed in-stage
        collectives (shard_map manual-subset axes).  pp×sp (ring attention
        inside a manual region) is rejected up front."""
        if mesh is not None and dict(mesh.shape).get("pp", 1) > 1:
            shape = dict(mesh.shape)
            if shape.get("sp", 1) > 1:
                raise NotImplementedError(
                    "pp composes with dp/fsdp (batch sharding) and tp; "
                    "pp×sp is not supported yet"
                )
            if shape.get("tp", 1) > 1 and self.config.pp_schedule == "1f1b":
                raise NotImplementedError("1f1b composes with dp/fsdp only")
            specs = self.param_pspecs(None)

            def relayer(spec):
                if not isinstance(spec, P):
                    return spec
                parts = list(spec)
                if parts and parts[0] == "fsdp":
                    parts[0] = "pp"  # stage dim, not ZeRO dim, under pp
                return P(*parts)

            specs["layers"] = {
                k: relayer(v) for k, v in specs["layers"].items()
            }
            return specs
        layers = {
            "ln1_scale": P("fsdp", None),
            "ln1_bias": P("fsdp", None),
            "ln2_scale": P("fsdp", None),
            "ln2_bias": P("fsdp", None),
            "qkv_w": P("fsdp", None, "tp"),
            "qkv_b": P("fsdp", "tp"),
            "proj_w": P("fsdp", "tp", None),
            "proj_b": P("fsdp", None),
        }
        if self.config.moe_experts:
            # experts shard over ep on their expert dim; router replicates
            layers.update(
                {
                    "router_w": P("fsdp", None, None),
                    "expert_in": P("fsdp", "ep", None, None),
                    "expert_out": P("fsdp", "ep", None, None),
                }
            )
        else:
            layers.update(
                {
                    "mlp_in_w": P("fsdp", None, "tp"),
                    "mlp_in_b": P("fsdp", "tp"),
                    "mlp_out_w": P("fsdp", "tp", None),
                    "mlp_out_b": P("fsdp", None),
                }
            )
        return {
            "wte": P("tp", None),
            "wpe": P(None, None),
            "ln_f": {"scale": P(None), "bias": P(None)},
            "layers": layers,
        }

    # ----------------------------------------------------------- forward

    def _layer(self, x: jax.Array, layer_params: Dict[str, jax.Array], mesh) -> jax.Array:
        cfg = self.config
        cd = cfg.compute_dtype
        B, S, E = x.shape
        H, D = cfg.n_head, cfg.head_dim

        from jax.ad_checkpoint import checkpoint_name

        def ln(h, scale, bias, name):
            h32 = h.astype(jnp.float32)
            mu = h32.mean(-1, keepdims=True)
            var = ((h32 - mu) ** 2).mean(-1, keepdims=True)
            out = ((h32 - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias).astype(cd)
            # named for the "lite" remat policy: recompute-from-residual
            # instead of saving the [B,S,E] buffer
            return checkpoint_name(out, name)

        h = ln(x, layer_params["ln1_scale"].astype(jnp.float32), layer_params["ln1_bias"].astype(jnp.float32), "ln1_out")
        qkv = h @ layer_params["qkv_w"].astype(cd) + layer_params["qkv_b"].astype(cd)
        q, k_, v_ = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D)
        k_ = k_.reshape(B, S, H, D)
        v_ = v_.reshape(B, S, H, D)
        if cfg.use_ring_attention and mesh is not None and mesh.shape.get("sp", 1) > 1:
            # sequence parallelism: drop into SPMD-per-device code for the
            # attention only — the K/V ring rides ppermute over the sp axis
            import functools as _ft

            from ray_tpu.parallel.mesh import shard_map_compat
            from ray_tpu.parallel.ring_attention import ring_attention

            data = tuple(
                a for a in ("dp", "fsdp") if a in mesh.axis_names and mesh.shape[a] > 1
            )
            spec = jax.sharding.PartitionSpec(data or None, "sp", None, None)
            attn = shard_map_compat(
                _ft.partial(ring_attention, axis_name="sp", causal=True),
                mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )(q, k_, v_)
        else:
            attn = self._causal_attention(q, k_, v_)
        attn = attn.reshape(B, S, E)
        x = x + (attn @ layer_params["proj_w"].astype(cd) + layer_params["proj_b"].astype(cd))

        h = ln(x, layer_params["ln2_scale"].astype(jnp.float32), layer_params["ln2_bias"].astype(jnp.float32), "ln2_out")
        if cfg.moe_experts:
            x = x + self._moe_mlp(h, layer_params, mesh).astype(cd)
        else:
            h = h @ layer_params["mlp_in_w"].astype(cd) + layer_params["mlp_in_b"].astype(cd)
            h = checkpoint_name(jax.nn.gelu(h), "gelu_out")
            x = x + (h @ layer_params["mlp_out_w"].astype(cd) + layer_params["mlp_out_b"].astype(cd))
        return x

    def _moe_mlp(self, h: jax.Array, layer_params, mesh) -> jax.Array:
        """Top-1 switch MoE MLP: tokens all-to-all to their expert's device
        over the ep axis (parallel/moe.py).  ep==1 (or no mesh) runs the
        identical routed compute without collectives, so single-device and
        ep-sharded results agree at sufficient capacity."""
        import functools as _ft

        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel.mesh import shard_map_compat
        from ray_tpu.parallel.moe import moe_ffn

        cfg = self.config
        cd = cfg.compute_dtype
        B, S, E = h.shape
        flat = h.reshape(B * S, E)
        router = layer_params["router_w"].astype(cd)
        ein = layer_params["expert_in"].astype(cd)
        eout = layer_params["expert_out"].astype(cd)
        fn = _ft.partial(
            moe_ffn, axis_name="ep", capacity_factor=cfg.moe_capacity_factor
        )
        if mesh is None:
            # degenerate ep group of one: same math, no collectives
            import numpy as _np

            from jax.sharding import Mesh

            mesh1 = Mesh(_np.array(jax.devices()[:1]), ("ep",))
            out = shard_map_compat(
                fn,
                mesh1,
                in_specs=(P(None), P(None), P(None), P(None)),
                out_specs=P(None),
            )(flat, router, ein, eout)
            return out.reshape(B, S, E)
        if "ep" not in mesh.axis_names:
            raise NotImplementedError(
                "MoE needs an ep axis on the mesh (keep_unit_axes meshes "
                "always carry one)"
            )
        data_axes = tuple(
            a for a in ("dp", "fsdp", "ep") if a in mesh.axis_names and mesh.shape[a] > 1
        )
        out = shard_map_compat(
            fn,
            mesh,
            in_specs=(
                P(data_axes or None, None),
                P(None, None),
                P("ep", None, None),
                P("ep", None, None),
            ),
            out_specs=P(data_axes or None, None),
        )(flat, router, ein, eout)
        return out.reshape(B, S, E)

    def _causal_attention(self, q, k, v):
        from ray_tpu.ops.attention import causal_attention

        return causal_attention(
            q,
            k,
            v,
            impl=self.config.attention_impl,
            scores_dtype=self.config.attn_scores_dtype,
        )

    def backbone(
        self,
        params: Dict[str, Any],
        tokens: jax.Array,
        mesh=None,
    ) -> jax.Array:
        """tokens [B, S] int32 → final hidden states [B, S, E] in
        compute_dtype (post final layernorm, pre lm-head)."""
        cfg = self.config
        cd = cfg.compute_dtype
        B, S = tokens.shape
        x = params["wte"].astype(cd)[tokens] + params["wpe"].astype(cd)[:S][None]

        if cfg.remat and cfg.remat_policy == "dots":
            # dots + the splash kernel's named residuals: saving the ~25MB
            # of attention output/lse per layer avoids re-running the whole
            # fwd attention kernel inside the backward pass.  (Also saving
            # ln/gelu outputs was measured SLOWER — their recompute is
            # cheaper than the extra HBM round-trips.)
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names("splash_residuals"),
            )
        elif cfg.remat and cfg.remat_policy == "lite":
            policy = jax.checkpoint_policies.save_anything_except_these_names(
                "ln1_out", "ln2_out", "gelu_out"
            )
        else:
            policy = None

        def scan_body(x, layer_params):
            if cfg.remat:
                y = jax.checkpoint(
                    lambda x_, lp: self._layer(x_, lp, mesh), policy=policy
                )(x, layer_params)
            else:
                y = self._layer(x, layer_params, mesh)
            return y, None

        if mesh is not None and dict(mesh.shape).get("pp", 1) > 1:
            # GPipe over the pp axis: each stage scans its layer slice,
            # activations hop stage→stage by ppermute (parallel/pipeline.py)
            from ray_tpu.parallel.pipeline import make_pipeline

            def stage_fn(stage_layers, h):
                out, _ = jax.lax.scan(scan_body, h, stage_layers)
                return out

            pipe = make_pipeline(
                mesh,
                stage_fn,
                num_microbatches=cfg.pp_microbatches,
                batch_axes=("dp", "fsdp"),
            )
            x = pipe(params["layers"], x)
        else:
            x, _ = jax.lax.scan(scan_body, x, params["layers"])
        scale = params["ln_f"]["scale"].astype(jnp.float32)
        bias = params["ln_f"]["bias"].astype(jnp.float32)
        x32 = x.astype(jnp.float32)
        mu = x32.mean(-1, keepdims=True)
        var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
        x = (x32 - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias
        return x.astype(cd)

    def apply(
        self,
        params: Dict[str, Any],
        tokens: jax.Array,
        mesh=None,
    ) -> jax.Array:
        """tokens [B, S] int32 → logits [B, S, padded_vocab].

        Stays in bf16: the naive loss upcasts inside fused reductions —
        returning f32 here would materialize an extra [B,S,V] f32 tensor."""
        x = self.backbone(params, tokens, mesh)
        return x @ params["wte"].astype(self.config.compute_dtype).T

    def loss_and_grads_1f1b(
        self,
        params: Dict[str, Any],
        tokens: jax.Array,
        targets: jax.Array,
        mesh,
    ):
        """(loss, grads) via the explicit 1F1B pipeline schedule
        (parallel/pipeline.py pipeline_train_1f1b): embedding runs at
        stage 0, the final-norm + tied-head CE at the last stage, each
        per-microbatch — gradients match the GPipe/sequential path while
        live activations stay bounded by the pipe depth.  Composes with
        dp/fsdp batch sharding; tp/sp/ep under 1F1B are rejected."""
        import functools as _ft

        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel.mesh import shard_map_compat
        from ray_tpu.parallel.pipeline import pipeline_train_1f1b

        cfg = self.config
        cd = cfg.compute_dtype
        shape = dict(mesh.shape)
        if shape.get("tp", 1) > 1 or shape.get("sp", 1) > 1 or shape.get("ep", 1) > 1:
            raise NotImplementedError("1f1b composes with dp/fsdp only")
        pp = shape["pp"]
        batch_axes = tuple(
            a for a in ("dp", "fsdp") if a in mesh.axis_names and mesh.shape[a] > 1
        )

        def embed_fn(extra, tok_mb):
            S = tok_mb.shape[1]
            return extra["wte"].astype(cd)[tok_mb] + extra["wpe"].astype(cd)[:S][None]

        def stage_fn(stage_layers, h):
            def scan_body(x, layer_params):
                if cfg.remat:
                    y = jax.checkpoint(lambda x_, lp: self._layer(x_, lp, None))(
                        x, layer_params
                    )
                else:
                    y = self._layer(x, layer_params, None)
                return y, None

            out, _ = jax.lax.scan(scan_body, h, stage_layers)
            return out

        def loss_fn(extra, y, tgt_mb):
            scale = extra["ln_f"]["scale"].astype(jnp.float32)
            bias = extra["ln_f"]["bias"].astype(jnp.float32)
            x32 = y.astype(jnp.float32)
            mu = x32.mean(-1, keepdims=True)
            var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
            h = ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias).astype(cd)
            logits = (h @ extra["wte"].astype(cd).T).astype(jnp.float32)
            if cfg.padded_vocab != cfg.vocab_size:
                pad_mask = jnp.arange(cfg.padded_vocab) >= cfg.vocab_size
                logits = jnp.where(pad_mask, -1e30, logits)
            label_logit = jnp.take_along_axis(logits, tgt_mb[..., None], axis=-1)[..., 0]
            lse = jax.nn.logsumexp(logits, axis=-1)
            return (lse - label_logit).mean()

        def body(stage_layers, extra, tok_l, tgt_l):
            B = tok_l.shape[0]
            M = max(
                d
                for d in range(1, min(cfg.pp_microbatches, B) + 1)
                if B % d == 0
            )
            tok_mbs = tok_l.reshape(M, B // M, *tok_l.shape[1:])
            tgt_mbs = tgt_l.reshape(M, B // M, *tgt_l.shape[1:])
            loss, sg, eg = pipeline_train_1f1b(
                stage_layers,
                extra,
                tok_mbs,
                tgt_mbs,
                stage_fn=stage_fn,
                embed_fn=embed_fn,
                loss_fn=loss_fn,
                reduce_axes=batch_axes,
            )
            return loss, sg, eg

        def layer_spec(leaf):
            return P("pp", *([None] * (leaf.ndim - 1)))

        extra_params = {k: v for k, v in params.items() if k != "layers"}
        layer_specs = jax.tree.map(layer_spec, params["layers"])
        extra_specs = jax.tree.map(lambda _: P(), extra_params)
        data_spec = P(batch_axes or None, None)

        loss, sg, eg = shard_map_compat(
            body,
            mesh,
            in_specs=(layer_specs, extra_specs, data_spec, data_spec),
            out_specs=(P(), layer_specs, extra_specs),
        )(params["layers"], extra_params, tokens, targets)
        grads = dict(eg)
        grads["layers"] = sg
        return loss, grads

    def loss(
        self,
        params: Dict[str, Any],
        tokens: jax.Array,
        targets: jax.Array,
        mesh=None,
    ) -> jax.Array:
        """Mean next-token cross entropy; padded-vocab tail masked out.

        Default ("auto"/"fused") path: chunked linear-head CE — the [B,S,V]
        logits tensor never exists in HBM (ops/cross_entropy.py; the single
        biggest HBM consumer of the naive form).  "naive" keeps the
        full-logits path for layouts the chunked scan can't express
        (sequence axis sharded by sp ring attention)."""
        cfg = self.config
        impl = cfg.loss_impl
        if impl == "auto":
            sp = mesh is not None and mesh.shape.get("sp", 1) > 1
            if sp:
                impl = "naive"  # chunked scan can't express the sp layout
            else:
                # naive materializes the [B,S,V] logits (f32): faster when
                # it fits (no bwd recompute — measured 162 vs 174 ms at
                # b16/v5e), deadly when it doesn't.  Estimate the
                # PER-DEVICE footprint against a 4 GiB budget.
                shards = 1
                if mesh is not None:
                    for a in ("dp", "fsdp"):
                        shards *= dict(mesh.shape).get(a, 1)
                B, S = tokens.shape
                f32_bytes = B * S * cfg.padded_vocab * 4 // max(1, shards)
                impl = "naive" if f32_bytes <= (4 << 30) else "fused"
        if impl == "fused":
            from ray_tpu.ops.cross_entropy import fused_linear_cross_entropy

            x = self.backbone(params, tokens, mesh)
            w = params["wte"].astype(cfg.compute_dtype)
            chunk = cfg.loss_chunk or max(128, min(512, 8192 // max(1, tokens.shape[0])))
            return fused_linear_cross_entropy(
                x, w, targets, cfg.vocab_size, chunk
            )
        logits = self.apply(params, tokens, mesh).astype(jnp.float32)
        if cfg.padded_vocab != cfg.vocab_size:
            # select (fuses into the logsumexp reduction) instead of a
            # scatter, which would materialize a full [B,S,V] copy
            pad_mask = jnp.arange(cfg.padded_vocab) >= cfg.vocab_size
            logits = jnp.where(pad_mask, -1e30, logits)
        label_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        lse = jax.nn.logsumexp(logits, axis=-1)
        return (lse - label_logit).mean()
