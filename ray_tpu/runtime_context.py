"""Runtime context: who am I, where am I running.

Analog of the reference's ray.runtime_context
(reference: python/ray/runtime_context.py get_runtime_context()).
"""

from __future__ import annotations

from typing import Optional


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    @property
    def job_id(self):
        return self._worker.core_worker.job_id

    @property
    def node_id(self) -> Optional[bytes]:
        return self._worker.core_worker.node_id

    @property
    def worker_id(self):
        return self._worker.core_worker.worker_id

    @property
    def task_id(self) -> Optional[bytes]:
        return self._worker.core_worker.current_task_id

    @property
    def address_info(self) -> dict:
        return {"address": self._worker.address, "session_dir": self._worker.session_dir}

    def get_node_id(self) -> str:
        nid = self.node_id
        return nid.hex() if nid else ""

    def get_job_id(self) -> str:
        return self.job_id.hex()

    def get(self) -> dict:
        return {
            "job_id": self.job_id,
            "node_id": self.node_id,
            "worker_id": self.worker_id,
        }


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private import worker as worker_mod

    worker_mod._require_connected()
    return RuntimeContext(worker_mod.global_worker)
