from ray_tpu.job_submission.client import JobStatus, JobSubmissionClient  # noqa: F401
