"""Job submission: run an entrypoint command against a live cluster.

Analog of the reference's job API (reference: dashboard/modules/job/
job_manager.py JobManager — supervisor actor per job, status + log
tailing; SDK python/ray/job_submission/).  The supervisor actor spawns the
entrypoint subprocess with RAY_TPU_ADDRESS pointed at the cluster so the
job's ray_tpu.init(address="auto") attaches.
"""

from __future__ import annotations

import enum
import time
import uuid
from typing import Dict, Optional


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class _JobSupervisor:
    """Detached actor owning one job's subprocess."""

    def __init__(self, job_id: str, entrypoint: str, env: Optional[dict], address: str):
        import os
        import subprocess
        import tempfile

        self.job_id = job_id
        self.entrypoint = entrypoint
        self.log_path = tempfile.mktemp(prefix=f"ray_tpu_job_{job_id}_", suffix=".log")
        penv = dict(os.environ)
        penv.update(env or {})
        penv["RAY_TPU_ADDRESS"] = address
        self._logf = open(self.log_path, "wb")
        self.proc = subprocess.Popen(
            entrypoint, shell=True, env=penv, stdout=self._logf, stderr=self._logf
        )
        self.stopped = False

    def status(self) -> str:
        rc = self.proc.poll()
        if rc is None:
            return JobStatus.RUNNING
        if self.stopped:
            return JobStatus.STOPPED
        return JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED

    def stop(self):
        self.stopped = True
        try:
            self.proc.terminate()
        except OSError:
            pass
        return True

    def logs(self) -> str:
        self._logf.flush()
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""


class JobSubmissionClient:
    def __init__(self, address: Optional[str] = None):
        import ray_tpu
        from ray_tpu._private import worker as worker_mod

        if not worker_mod.global_worker.connected:
            ray_tpu.init(address=address)
        self._address = worker_mod.global_worker.address

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        job_id: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> str:
        import ray_tpu

        job_id = job_id or f"raytpu_job_{uuid.uuid4().hex[:8]}"
        env = (runtime_env or {}).get("env_vars")
        if priority is not None:
            # job-level scheduling band: the entrypoint's ray_tpu.init()
            # picks it up as its default priority (see _private/worker.py)
            env = dict(env or {})
            env["RAY_TPU_JOB_PRIORITY"] = str(int(priority))
        cls = ray_tpu.remote(_JobSupervisor)
        cls.options(name=f"_job_{job_id}", lifetime="detached", num_cpus=0).remote(
            job_id, entrypoint, env, self._address
        )
        return job_id

    def _supervisor(self, job_id: str):
        import ray_tpu

        return ray_tpu.get_actor(f"_job_{job_id}")

    def get_job_status(self, job_id: str) -> JobStatus:
        import ray_tpu

        try:
            sup = self._supervisor(job_id)
        except ValueError:
            return JobStatus.STOPPED
        return JobStatus(ray_tpu.get(sup.status.remote(), timeout=30))

    def get_job_logs(self, job_id: str) -> str:
        import ray_tpu

        return ray_tpu.get(self._supervisor(job_id).logs.remote(), timeout=30)

    def stop_job(self, job_id: str) -> bool:
        import ray_tpu

        return ray_tpu.get(self._supervisor(job_id).stop.remote(), timeout=30)

    def wait_until_finish(self, job_id: str, timeout: float = 300) -> JobStatus:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
