from ray_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: F401
