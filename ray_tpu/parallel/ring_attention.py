"""Ring attention: exact attention over sequences sharded across devices.

A capability the reference framework lacks entirely (SURVEY §5
"Long-context / sequence parallelism: Absent") — built here as prescribed:
blockwise online-softmax attention with K/V blocks rotating around the
`sp` mesh axis via ppermute, so each device only ever holds seq/n of the
keys while computing exact global attention.  Communication (one K/V block
per step) overlaps with the blockwise compute and rides the ICI ring.

Shapes (per device): q, k, v — [batch, seq_local, num_heads, head_dim].
Use under shard_map with sequence sharded over `axis_name`:

    fn = shard_map(partial(ring_attention, axis_name="sp"), mesh=mesh,
                   in_specs=P(None, "sp", None, None), out_specs=P(None, "sp", None, None))

Design refs: Liu et al., "Ring Attention with Blockwise Transformers"
(PAPERS.md); flash-attention online softmax for the inner block update.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, mask, sm_scale):
    """One q-block × kv-block partial attention with online-softmax stats.

    Returns (unnormalized_out, row_max, row_sum) in f32.
    q: [b, sq, h, d]; k, v: [b, skv, h, d]; mask: [sq, skv] or None.
    """
    q32 = q.astype(jnp.float32)
    k32 = k.astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k32) * sm_scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [b, h, q]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(scores - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [b, h, q]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m_safe, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention with sequence sharded over `axis_name`.

    Each of the n ring steps attends the local q block against the K/V
    block currently resident, then rotates K/V one hop (ppermute).  Online
    softmax (running max m, denominator l, unnormalized accumulator o)
    makes the result exact regardless of arrival order.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    if sm_scale is None:
        sm_scale = d**-0.5

    q_pos = my_idx * s_local + jnp.arange(s_local)  # global positions of q rows

    o = jnp.zeros((b, s_local, h, d), jnp.float32)
    m = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)

    def step(carry, step_idx):
        o, m, l, k_cur, v_cur = carry
        src_idx = (my_idx - step_idx) % n  # whose K/V block we hold now
        if causal:
            kv_pos = src_idx * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = None
        o_blk, m_blk, l_blk = _block_attn(q, k_cur, v_cur, mask, sm_scale)
        # online-softmax merge of (o, m, l) with the new block stats
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)  # rescale of old accumulator
        beta = jnp.exp(m_blk - m_new)
        l_new = l * alpha + l_blk * beta
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + o_blk * beta.transpose(0, 2, 1)[..., None]
        # rotate K/V one hop around the ring (overlappable with compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(step, (o, m, l, k, v), jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(mesh, *, causal: bool = True, axis_name: str = "sp"):
    """shard_map-wrapped ring attention over `mesh` (batch replicated over
    data axes by the caller's outer pjit; here only `sp` is mapped)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    spec = P(None, axis_name, None, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    return shard_map_compat(fn, mesh, in_specs=(spec, spec, spec), out_specs=spec)
