"""Expert parallelism: MoE FFN with all-to-all dispatch over the `ep` axis.

A capability absent from the reference (SURVEY §2.4 "Expert parallel
(EP/MoE): absent") — built the TPU way: experts shard over the `ep` mesh
axis, tokens route to experts via `lax.all_to_all` (one ICI all-to-all
each way), top-1 switch routing with capacity dropping (Switch
Transformer; see PAPERS.md).

Per-device shapes under shard_map: tokens [B_local, S, E]; each device
hosts n_experts/ep_size experts.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def moe_ffn(
    x: jax.Array,  # [tokens_local, E] per device
    router_w: jax.Array,  # [E, n_experts]
    expert_in: jax.Array,  # [experts_local, E, H]
    expert_out: jax.Array,  # [experts_local, H, E]
    *,
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
) -> jax.Array:
    """Top-1 routed expert FFN.  Runs inside shard_map over `axis_name`."""
    ep = lax.psum(1, axis_name)
    n_tokens, E = x.shape
    experts_local = expert_in.shape[0]
    n_experts = ep * experts_local
    capacity = max(1, int(capacity_factor * n_tokens / n_experts))

    logits = x @ router_w  # [T, n_experts]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    # position of each token within its expert's queue; drop beyond capacity
    one_hot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [T, X]
    pos_in_expert = (jnp.cumsum(one_hot, axis=0) - 1) * one_hot  # [T, X]
    pos = pos_in_expert.max(axis=1)  # [T]
    keep = pos < capacity

    # dispatch buffer: [n_experts, capacity, E]
    dispatch = jnp.zeros((n_experts, capacity, E), x.dtype)
    dispatch = dispatch.at[expert_idx, jnp.where(keep, pos, 0)].add(
        jnp.where(keep[:, None], x, 0.0)
    )
    # all-to-all: expert dim split across devices, each device gets its
    # experts' tokens from every peer → [ep, experts_local, capacity, E]
    shaped = dispatch.reshape(ep, experts_local, capacity, E)
    received = lax.all_to_all(shaped, axis_name, split_axis=0, concat_axis=0)
    # [ep(peer), experts_local, capacity, E] → per expert: [ep*capacity, E]
    tokens_per_expert = received.transpose(1, 0, 2, 3).reshape(
        experts_local, ep * capacity, E
    )

    # expert FFN (batched over local experts — one MXU matmul pair)
    h = jax.nn.gelu(jnp.einsum("xte,xeh->xth", tokens_per_expert, expert_in))
    y = jnp.einsum("xth,xhe->xte", h, expert_out)

    # route back: inverse all-to-all
    y = y.reshape(experts_local, ep, capacity, E).transpose(1, 0, 2, 3)
    returned = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0)
    combined = returned.reshape(n_experts, capacity, E)

    out = combined[expert_idx, jnp.where(keep, pos, 0)]
    out = jnp.where(keep[:, None], out * gate[:, None], 0.0)
    return out


def make_moe_ffn(mesh, *, axis_name: str = "ep", capacity_factor: float = 1.25):
    """shard_map wrapper: tokens sharded over `ep` (data-style), experts
    sharded over `ep` (their leading dim)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    fn = functools.partial(moe_ffn, axis_name=axis_name, capacity_factor=capacity_factor)
    return shard_map_compat(
        fn,
        mesh,
        in_specs=(
            P(axis_name, None),
            P(None, None),
            P(axis_name, None, None),
            P(axis_name, None, None),
        ),
        out_specs=P(axis_name, None),
    )
