"""Device-mesh construction: the substrate of every parallelism strategy.

This is the TPU-native answer to the reference's per-strategy plumbing
(SURVEY §2.4): where the reference wires NCCL process groups per strategy
(DDP via torch PGs, collective groups via cupy NCCL), here every strategy —
DP / ZeRO / TP / PP / SP / EP — is an *axis of one jax Mesh*, and XLA
inserts the collectives (psum over `dp`, all-gather over `fsdp`, ppermute
over `sp`, all-to-all over `ep`) that ride ICI.

Axis conventions (matching the scaling-book vocabulary):
  dp    — data parallel (gradient psum)
  fsdp  — ZeRO-style parameter/optimizer sharding (all-gather on use)
  tp    — tensor parallel (intra-layer, megatron-style)
  pp    — pipeline stages
  sp    — sequence/context parallel (ring attention)
  ep    — expert parallel (MoE all-to-all)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "ep", "tp")


@dataclass
class MeshConfig:
    """Logical mesh shape.  Unspecified axes default to 1 and are dropped
    unless keep_unit_axes is set (kept axes still appear in PartitionSpecs,
    which makes specs portable across scales)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    keep_unit_axes: bool = True

    def axis_sizes(self) -> Dict[str, int]:
        return {name: int(getattr(self, name)) for name in AXIS_ORDER}

    def total_devices(self) -> int:
        n = 1
        for v in self.axis_sizes().values():
            n *= v
        return n

    @classmethod
    def for_devices(cls, n: int, *, tp: int = 1, sp: int = 1, fsdp: int = 1) -> "MeshConfig":
        """Fill the dp axis with whatever is left after explicit axes."""
        rest = tp * sp * fsdp
        if n % rest:
            raise ValueError(f"{n} devices not divisible by tp*sp*fsdp={rest}")
        return cls(dp=n // rest, tp=tp, sp=sp, fsdp=fsdp)


def make_mesh(config: MeshConfig, devices: Optional[Sequence] = None):
    """Build a jax.sharding.Mesh laid out so the fastest-varying axes (tp,
    last in AXIS_ORDER) map to nearest ICI neighbors — tensor-parallel
    collectives are the most latency-sensitive, so they get the shortest
    rings (the standard v4/v5 layout recipe)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    sizes = config.axis_sizes()
    needed = config.total_devices()
    if needed > len(devices):
        raise ValueError(f"mesh needs {needed} devices, have {len(devices)}")
    devices = list(devices)[:needed]
    if config.keep_unit_axes:
        names = list(AXIS_ORDER)
        shape = [sizes[a] for a in names]
    else:
        names = [a for a in AXIS_ORDER if sizes[a] > 1] or ["dp"]
        shape = [sizes[a] for a in names]
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(names))


def shard_map_compat(fn, mesh, in_specs, out_specs, manual_axes=None):
    """shard_map across jax versions (jax.shard_map vs experimental;
    check_vma vs check_rep) — the single shared wrapper for every SPMD
    helper in this package.

    manual_axes: restrict manual collectives to this subset of mesh axes —
    the REST stay compiler-managed ("auto") inside the body, so e.g. a
    GPipe schedule manual over pp can keep tp-sharded in-stage matmuls
    with XLA-inserted collectives (pp×tp composition)."""
    try:
        from jax import shard_map as _sm

        kw = {"check_vma": False}
        if manual_axes is not None and set(manual_axes) != set(mesh.axis_names):
            kw["axis_names"] = frozenset(manual_axes)
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

        kw = {"check_rep": False}
        if manual_axes is not None and set(manual_axes) != set(mesh.axis_names):
            kw["auto"] = frozenset(set(mesh.axis_names) - set(manual_axes))
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def data_pspec(mesh) -> "object":
    """PartitionSpec for a [batch, ...] input: batch sharded over every
    data-ish axis present (dp and fsdp both consume batch)."""
    from jax.sharding import PartitionSpec as P

    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    return P(batch_axes if batch_axes else None)


def replicated_pspec() -> "object":
    from jax.sharding import PartitionSpec as P

    return P()


def batch_size_multiple(mesh) -> int:
    """Global batch must divide by this (product of data axes)."""
    n = 1
    for a in ("dp", "fsdp"):
        if a in mesh.axis_names:
            n *= mesh.shape[a]
    return n
