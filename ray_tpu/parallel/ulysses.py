"""Ulysses sequence parallelism: head-sharded attention via all-to-all.

The second SP strategy from SURVEY §5: instead of rotating K/V around a
ring (ring_attention.py), re-shard [seq-sharded, all heads] →
[all seq, head-sharded] with one all-to-all, run full attention per head
group, and all-to-all back (DeepSpeed-Ulysses; see PAPERS.md).  Cheaper in
latency than the ring for moderate sequence lengths (2 collectives total
instead of n-1 rotations); requires num_heads % sp == 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _attention(q, k, v, causal: bool):
    B, S, H, D = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (D**-0.5)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Per-device shapes in: [B, S_local, H, D] (seq sharded).  Internally
    re-shards to [B, S_full, H_local, D] (heads sharded), attends, and
    re-shards back."""
    sp = lax.psum(1, axis_name)
    B, s_local, H, D = q.shape
    assert H % sp == 0, f"num_heads {H} must divide sp {sp}"

    def to_heads(x):
        # [B, s_local, H, D] -> [sp, B, s_local, H/sp, D] -> a2a over seq
        parts = x.reshape(B, s_local, sp, H // sp, D).transpose(2, 0, 1, 3, 4)
        out = lax.all_to_all(parts, axis_name, split_axis=0, concat_axis=0)
        # [sp(seq chunks), B, s_local, H/sp, D] -> [B, S_full, H/sp, D]
        return out.transpose(1, 0, 2, 3, 4).reshape(B, sp * s_local, H // sp, D)

    def to_seq(x):
        # inverse of to_heads
        parts = x.reshape(B, sp, s_local, H // sp, D).transpose(1, 0, 2, 3, 4)
        out = lax.all_to_all(parts, axis_name, split_axis=0, concat_axis=0)
        # [sp(head groups), B, s_local, H/sp, D] -> [B, s_local, H, D]
        return out.transpose(1, 2, 0, 3, 4).reshape(B, s_local, H, D)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _attention(qh, kh, vh, causal)
    return to_seq(out)


def make_ulysses_attention(mesh, *, causal: bool = True, axis_name: str = "sp"):
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    spec = P(None, axis_name, None, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name, causal=causal)
    return shard_map_compat(fn, mesh, in_specs=(spec, spec, spec), out_specs=spec)
