"""Pipeline parallelism: GPipe microbatch schedule over the `pp` mesh axis.

Absent from the reference (SURVEY §2.4 "Pipeline parallel: absent") —
built as prescribed: stage-sharded layers, activations hop stage→stage via
ppermute each schedule tick, M microbatches fill the pipe (bubble fraction
(pp-1)/(M+pp-1)).  The whole schedule is one differentiable jax program:
jax.grad through it yields the backward pipeline automatically (ppermute
transposes to the reverse hop).

Usage: params' layer-stacked leaves are sharded over `pp` on the layer
axis; `pipeline_apply` runs under shard_map with stage_fn processing this
stage's [layers_per_stage, ...] slice.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_params: Any,  # this stage's layer slice (leading dim L/pp)
    x: jax.Array,  # [B, ...] full batch, replicated across stages
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
    num_microbatches: int = 4,
) -> jax.Array:
    """Run x through all pp stages with a GPipe schedule.  Returns the
    final-stage output, broadcast to every stage (so downstream replicated
    ops — final norm, head — run without a gather)."""
    pp = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    B = x.shape[0]
    # microbatch count must divide the (per-data-shard) batch: fall back to
    # the largest divisor of B ≤ requested (exactness is unaffected — GPipe
    # computes the same full-batch gradient at any M; fewer microbatches
    # only widens the bubble)
    M = max(d for d in range(1, min(num_microbatches, B) + 1) if B % d == 0)
    mbs = x.reshape(M, B // M, *x.shape[1:])

    perm = [(i, (i + 1) % pp) for i in range(pp)]
    zero_mb = jnp.zeros_like(mbs[0])
    outputs0 = jnp.zeros_like(mbs)

    def body(carry, t):
        prev_from_left, outputs = carry
        # stage 0 feeds microbatch t (while available); others take the
        # activation that arrived from the previous stage
        feed_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(mbs, feed_idx, keepdims=False)
        inp = jnp.where(stage == 0, first_in, prev_from_left)
        out = stage_fn(stage_params, inp)
        # last stage emits microbatch t-(pp-1) once the pipe is full
        out_idx = t - (pp - 1)
        write = (stage == pp - 1) & (out_idx >= 0)
        safe_idx = jnp.clip(out_idx, 0, M - 1)
        candidate = lax.dynamic_update_index_in_dim(outputs, out, safe_idx, axis=0)
        outputs = jnp.where(write, candidate, outputs)
        # hop activations one stage to the right
        nxt = lax.ppermute(out, axis_name, perm)
        return (nxt, outputs), None

    steps = M + pp - 1
    (_, outputs), _ = lax.scan(body, (zero_mb, outputs0), jnp.arange(steps))
    # broadcast the last stage's collected outputs to all stages
    outputs = lax.psum(jnp.where(stage == pp - 1, outputs, 0.0), axis_name)
    return outputs.reshape(B, *x.shape[1:])


def make_pipeline(
    mesh,
    stage_fn: Callable,
    *,
    axis_name: str = "pp",
    num_microbatches: int = 4,
    layer_axis: int = 0,
    batch_axes: Tuple[str, ...] = (),
):
    """shard_map wrapper: layer-stacked params sharded over `pp`, batch
    sharded over `batch_axes` (dp/fsdp; each data shard runs its own GPipe
    schedule on its microbatches), final output sharded the same way.

    Every leaf must be layer-stacked: shape[layer_axis] divisible by the
    pp size.  Mixed trees (stacked layers + replicated extras like a final
    norm) must keep the extras OUTSIDE the pipelined call — enforced here
    rather than silently mis-sharded."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    pp_size = mesh.shape[axis_name]
    batch_axes = tuple(
        a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1
    )

    def specs_for(tree):
        def leaf_spec(leaf):
            nd = getattr(leaf, "ndim", 0)
            shape = getattr(leaf, "shape", ())
            if nd <= layer_axis or shape[layer_axis] % pp_size != 0:
                raise ValueError(
                    f"pipeline params must be layer-stacked on axis {layer_axis} "
                    f"with a multiple of pp={pp_size} layers; got shape {shape}. "
                    f"Keep replicated extras (embeddings, final norm) outside "
                    f"the pipelined stage_fn."
                )
            parts = [None] * nd
            parts[layer_axis] = axis_name
            return P(*parts)

        return jax.tree.map(leaf_spec, tree)

    def wrapped(stage_params, x):
        fn = functools.partial(
            pipeline_apply,
            stage_fn=stage_fn,
            axis_name=axis_name,
            num_microbatches=num_microbatches,
        )
        x_spec = P(batch_axes or None, *([None] * (x.ndim - 1)))
        return shard_map_compat(
            fn, mesh, in_specs=(specs_for(stage_params), x_spec), out_specs=x_spec
        )(stage_params, x)

    return wrapped
