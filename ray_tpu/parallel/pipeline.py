"""Pipeline parallelism: GPipe microbatch schedule over the `pp` mesh axis.

Absent from the reference (SURVEY §2.4 "Pipeline parallel: absent") —
built as prescribed: stage-sharded layers, activations hop stage→stage via
ppermute each schedule tick, M microbatches fill the pipe (bubble fraction
(pp-1)/(M+pp-1)).  The whole schedule is one differentiable jax program:
jax.grad through it yields the backward pipeline automatically (ppermute
transposes to the reverse hop).

Usage: params' layer-stacked leaves are sharded over `pp` on the layer
axis; `pipeline_apply` runs under shard_map with stage_fn processing this
stage's [layers_per_stage, ...] slice.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_params: Any,  # this stage's layer slice (leading dim L/pp)
    x: jax.Array,  # [B, ...] full batch, replicated across stages
    stage_t: jax.Array,  # [1] int32 — this stage's index, fed as pp-sharded data
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
    pp: int,
    num_microbatches: int = 4,
) -> jax.Array:
    """Run x through all pp stages with a GPipe schedule.  Returns the
    final-stage output, broadcast to every stage (so downstream replicated
    ops — final norm, head — run without a gather).

    The stage index arrives as DATA (an arange sharded over pp) and the
    pipe depth is static, instead of lax.axis_index/psum(1): under the
    partially-manual shard_map that pp×tp composition needs (tp stays
    auto), axis_index lowers to a PartitionId instruction the SPMD
    partitioner rejects (UNIMPLEMENTED on current XLA CPU builds)."""
    stage = stage_t[0]
    B = x.shape[0]
    # microbatch count must divide the (per-data-shard) batch: fall back to
    # the largest divisor of B ≤ requested (exactness is unaffected — GPipe
    # computes the same full-batch gradient at any M; fewer microbatches
    # only widens the bubble)
    M = max(d for d in range(1, min(num_microbatches, B) + 1) if B % d == 0)
    mbs = x.reshape(M, B // M, *x.shape[1:])

    perm = [(i, (i + 1) % pp) for i in range(pp)]
    zero_mb = jnp.zeros_like(mbs[0])
    outputs0 = jnp.zeros_like(mbs)

    def body(carry, t):
        prev_from_left, outputs = carry
        # stage 0 feeds microbatch t (while available); others take the
        # activation that arrived from the previous stage
        feed_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(mbs, feed_idx, keepdims=False)
        inp = jnp.where(stage == 0, first_in, prev_from_left)
        out = stage_fn(stage_params, inp)
        # last stage emits microbatch t-(pp-1) once the pipe is full
        out_idx = t - (pp - 1)
        write = (stage == pp - 1) & (out_idx >= 0)
        safe_idx = jnp.clip(out_idx, 0, M - 1)
        candidate = lax.dynamic_update_index_in_dim(outputs, out, safe_idx, axis=0)
        outputs = jnp.where(write, candidate, outputs)
        # hop activations one stage to the right
        nxt = lax.ppermute(out, axis_name, perm)
        return (nxt, outputs), None

    steps = M + pp - 1
    (_, outputs), _ = lax.scan(body, (zero_mb, outputs0), jnp.arange(steps))
    # broadcast the last stage's collected outputs to all stages
    outputs = lax.psum(jnp.where(stage == pp - 1, outputs, 0.0), axis_name)
    return outputs.reshape(B, *x.shape[1:])


def pipeline_train_1f1b(
    stage_params: Any,  # this stage's layer slice (leading dim L/pp)
    extra_params: Any,  # replicated params for embed/loss (wte, wpe, ln_f)
    tokens_mbs: jax.Array,  # [M, mb, S] int — microbatched stage-0 feed
    targets_mbs: jax.Array,  # [M, mb, S] int — last-stage loss labels
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    embed_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
    axis_name: str = "pp",
    reduce_axes: Tuple[str, ...] = (),
):
    """1F1B-flush training schedule with EXPLICIT per-microbatch backward:
    each pair-tick a stage runs one forward and one backward (vjp), so the
    live-activation set is a ring of min(M, 2·pp−1) stage inputs instead of
    the (M+pp−1) scan carries jax.grad saves through the GPipe schedule
    (reference gap: SURVEY §2.4 "Pipeline parallel: absent"; schedule per
    Megatron-LM's non-interleaved 1F1B).

    Honest accounting for this lockstep-SPMD realization: every stage
    executes both the forward and backward branch each tick (masked), so
    wall-clock matches GPipe at equal M (ticks M+2·pp−2 vs 2(M+pp−1)
    phase-ticks) — the 1F1B win is PEAK MEMORY, which is what lets you
    raise M at a fixed activation budget and shrink the bubble fraction
    (pp−1)/(M+pp−1) that way.  The MPMD bubble halving needs per-stage
    programs (actor pipelines), not one SPMD program.

    The last stage seeds cotangents from ``loss_fn`` (computed on ITS
    microbatch each backward tick); stage 0 additionally backprops
    ``embed_fn``.  Returns (mean_loss, stage_grads, extra_grads) — stage
    grads live per-stage (layer-sharded over pp), extra grads and loss are
    psum'd across stages.
    """
    pp = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    is_first = stage == 0
    is_last = stage == pp - 1
    M = tokens_mbs.shape[0]
    R = min(M, 2 * pp - 1)  # in-flight ring depth (1F1B memory bound)

    send_right = [(i, (i + 1) % pp) for i in range(pp)]
    send_left = [((i + 1) % pp, i) for i in range(pp)]

    x0 = embed_fn(extra_params, tokens_mbs[0])
    zero_x = jnp.zeros_like(x0)
    zero_ring = jnp.zeros((R, *x0.shape), x0.dtype)
    zero_sg = jax.tree.map(jnp.zeros_like, stage_params)
    zero_eg = jax.tree.map(jnp.zeros_like, extra_params)

    def tick(carry, u):
        act_in, ct_in, ring, sg, eg, loss_acc = carry
        # ---- schedule: F of mb i, B of mb k this pair-tick (masked)
        i = u - stage
        f_valid = (i >= 0) & (i < M)
        k = u - (2 * (pp - 1) - stage)
        b_valid = (k >= 0) & (k < M)
        i_c = jnp.clip(i, 0, M - 1)
        k_c = jnp.clip(k, 0, M - 1)

        # ---- forward
        fed = embed_fn(extra_params, lax.dynamic_index_in_dim(tokens_mbs, i_c, keepdims=False))
        x_in = jnp.where(is_first, fed, act_in)
        y_f = stage_fn(stage_params, x_in)
        ring = jnp.where(
            f_valid,
            lax.dynamic_update_index_in_dim(ring, x_in, i_c % R, axis=0),
            ring,
        )

        # ---- backward (recompute fwd from the saved stage input)
        x_b = lax.dynamic_index_in_dim(ring, k_c % R, keepdims=False)
        y_b, pull = jax.vjp(stage_fn, stage_params, x_b)
        tgt = lax.dynamic_index_in_dim(targets_mbs, k_c, keepdims=False)
        mb_loss, lpull = jax.vjp(lambda e, y: loss_fn(e, y, tgt), extra_params, y_b)
        de_loss, dy_loss = lpull(jnp.ones_like(mb_loss))
        ct_y = jnp.where(is_last, dy_loss, ct_in)
        dp, dx = pull(ct_y)

        # stage-0 backward continues through the embedding
        _, epull = jax.vjp(embed_fn, extra_params, lax.dynamic_index_in_dim(tokens_mbs, k_c, keepdims=False))
        de_embed, _ = epull(dx)

        bmask = b_valid.astype(jnp.float32)
        sg = jax.tree.map(lambda a, g: a + bmask * g.astype(a.dtype), sg, dp)
        lastmask = (b_valid & is_last).astype(jnp.float32)
        firstmask = (b_valid & is_first).astype(jnp.float32)
        eg = jax.tree.map(
            lambda a, gl, ge: a
            + lastmask * gl.astype(a.dtype)
            + firstmask * ge.astype(a.dtype),
            eg,
            de_loss,
            de_embed,
        )
        loss_acc = loss_acc + lastmask * mb_loss.astype(jnp.float32)

        # ---- hops: activations right, cotangents left
        act_nxt = lax.ppermute(jnp.where(f_valid, y_f, zero_x), axis_name, send_right)
        ct_nxt = lax.ppermute(jnp.where(b_valid, dx, zero_x), axis_name, send_left)
        return (act_nxt, ct_nxt, ring, sg, eg, loss_acc), None

    ticks = M + 2 * (pp - 1)
    (_, _, _, sg, eg, loss_acc), _ = lax.scan(
        tick,
        (zero_x, zero_x, zero_ring, zero_sg, zero_eg, jnp.float32(0.0)),
        jnp.arange(ticks),
    )
    # extras & loss were produced on specific stages: share them
    eg = jax.tree.map(lambda g: lax.psum(g, axis_name), eg)
    loss = lax.psum(loss_acc, axis_name) / M
    sg = jax.tree.map(lambda g: g / M, sg)
    eg = jax.tree.map(lambda g: g / M, eg)
    # data-parallel mean across batch shards (this function returns REAL
    # grads from inside shard_map, so the dp/fsdp reduction that pjit's
    # autodiff would have inserted must happen here)
    for ax in reduce_axes:
        n = lax.psum(1, ax)
        sg = jax.tree.map(lambda g: lax.psum(g, ax) / n, sg)
        eg = jax.tree.map(lambda g: lax.psum(g, ax) / n, eg)
        loss = lax.psum(loss, ax) / n
    return loss, sg, eg


def pipeline_apply_stacked(
    stacked_params: Any,  # leaves [pp, L/pp, ...] — stage dim explicit
    x: jax.Array,  # [B, ...] full (per-jit-view) batch
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    mesh,
    axis_name: str = "pp",
    pp: int,
    num_microbatches: int = 4,
    batch_axes: Tuple[str, ...] = (),
) -> jax.Array:
    """GPipe in pure AUTO-sharded form: the stage dimension is a real
    array axis sharded over `axis_name`, the per-tick stage compute is a
    ``vmap`` over it, and the stage→stage hop is ``jnp.roll`` on that
    axis (XLA lowers it to a collective-permute).  No shard_map at all —
    which is the point: the partially-manual form (manual pp, auto tp)
    trips partitioner bugs on current XLA builds (PartitionId
    UNIMPLEMENTED / manual-subgroup check crashes), while this
    formulation leaves tp-sharded in-stage matmuls entirely to the
    compiler.  Used by make_pipeline whenever the mesh carries a real
    auto axis (pp×tp composition)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    B = x.shape[0]
    M = max(d for d in range(1, min(num_microbatches, B) + 1) if B % d == 0)
    mbs = x.reshape(M, B // M, *x.shape[1:])
    rest = (None,) * (x.ndim - 1)
    acts_sharding = NamedSharding(mesh, P(axis_name, batch_axes or None, *rest))
    A = jax.lax.with_sharding_constraint(
        jnp.zeros((pp,) + mbs.shape[1:], x.dtype), acts_sharding
    )
    outputs0 = jnp.zeros_like(mbs)
    # stage-0 selector, broadcast over the microbatch dims
    sel_first = (jnp.arange(pp) == 0).reshape((pp,) + (1,) * x.ndim)
    vstage = jax.vmap(stage_fn, in_axes=(0, 0))

    def body(carry, t):
        A, outputs = carry
        feed = lax.dynamic_index_in_dim(
            mbs, jnp.clip(t, 0, M - 1), keepdims=False
        )
        inp = jnp.where(sel_first, feed[None], A)
        out = vstage(stacked_params, inp)  # [pp, mb, ...]
        out = jax.lax.with_sharding_constraint(out, acts_sharding)
        out_idx = t - (pp - 1)
        candidate = lax.dynamic_update_index_in_dim(
            outputs, out[pp - 1], jnp.clip(out_idx, 0, M - 1), axis=0
        )
        outputs = jnp.where(out_idx >= 0, candidate, outputs)
        # hop activations one stage to the right (ring, like ppermute in
        # the manual form; stage 0 ignores the wrapped value — it feeds)
        A = jnp.roll(out, 1, axis=0)
        return (A, outputs), None

    (_, outputs), _ = lax.scan(body, (A, outputs0), jnp.arange(M + pp - 1))
    return outputs.reshape(B, *x.shape[1:])


def make_pipeline(
    mesh,
    stage_fn: Callable,
    *,
    axis_name: str = "pp",
    num_microbatches: int = 4,
    layer_axis: int = 0,
    batch_axes: Tuple[str, ...] = (),
):
    """shard_map wrapper: layer-stacked params sharded over `pp`, batch
    sharded over `batch_axes` (dp/fsdp; each data shard runs its own GPipe
    schedule on its microbatches), final output sharded the same way.

    Every leaf must be layer-stacked: shape[layer_axis] divisible by the
    pp size.  Mixed trees (stacked layers + replicated extras like a final
    norm) must keep the extras OUTSIDE the pipelined call — enforced here
    rather than silently mis-sharded."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    pp_size = mesh.shape[axis_name]
    batch_axes = tuple(
        a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1
    )
    # real auto axes under the pipeline (tp): the partially-manual
    # shard_map form is broken on current XLA (see pipeline_apply_stacked)
    # — take the pure-auto formulation instead
    auto_axes = [
        a
        for a in mesh.axis_names
        if a != axis_name and a not in batch_axes and mesh.shape[a] > 1
    ]
    if auto_axes:
        from jax.sharding import PartitionSpec as P

        def wrapped_auto(stage_params, x):
            def restack(leaf):
                shape = leaf.shape
                if (
                    leaf.ndim <= layer_axis
                    or shape[layer_axis] % pp_size != 0
                ):
                    raise ValueError(
                        f"pipeline params must be layer-stacked on axis "
                        f"{layer_axis} with a multiple of pp={pp_size} "
                        f"layers; got shape {shape}."
                    )
                new_shape = (
                    shape[:layer_axis]
                    + (pp_size, shape[layer_axis] // pp_size)
                    + shape[layer_axis + 1 :]
                )
                leaf = leaf.reshape(new_shape)
                # pin only the stage dim; every other dim (incl. tp-sharded
                # ones) stays wherever propagation puts it
                parts = [P.UNCONSTRAINED] * leaf.ndim
                parts[layer_axis] = axis_name
                return jax.lax.with_sharding_constraint(
                    leaf, jax.sharding.NamedSharding(mesh, P(*parts))
                )

            stacked = jax.tree.map(restack, stage_params)
            return pipeline_apply_stacked(
                stacked,
                x,
                stage_fn,
                mesh=mesh,
                axis_name=axis_name,
                pp=pp_size,
                num_microbatches=num_microbatches,
                batch_axes=batch_axes,
            )

        return wrapped_auto

    def specs_for(tree):
        def leaf_spec(leaf):
            nd = getattr(leaf, "ndim", 0)
            shape = getattr(leaf, "shape", ())
            if nd <= layer_axis or shape[layer_axis] % pp_size != 0:
                raise ValueError(
                    f"pipeline params must be layer-stacked on axis {layer_axis} "
                    f"with a multiple of pp={pp_size} layers; got shape {shape}. "
                    f"Keep replicated extras (embeddings, final norm) outside "
                    f"the pipelined stage_fn."
                )
            parts = [None] * nd
            parts[layer_axis] = axis_name
            return P(*parts)

        return jax.tree.map(leaf_spec, tree)

    def wrapped(stage_params, x):
        fn = functools.partial(
            pipeline_apply,
            stage_fn=stage_fn,
            axis_name=axis_name,
            pp=pp_size,
            num_microbatches=num_microbatches,
        )
        x_spec = P(batch_axes or None, *([None] * (x.ndim - 1)))
        # the stage index rides in as pp-sharded data (see pipeline_apply:
        # axis_index is not available under the partial-manual shard_map)
        stage_ids = jnp.arange(pp_size, dtype=jnp.int32)
        # manual over pp + the batch axes only: other mesh axes (tp) stay
        # compiler-managed inside the stage, so tp-sharded layer weights
        # keep their XLA-inserted in-stage collectives under pp (pp×tp)
        return shard_map_compat(
            fn,
            mesh,
            in_specs=(specs_for(stage_params), x_spec, P(axis_name)),
            out_specs=x_spec,
            manual_axes=(axis_name, *batch_axes),
        )(stage_params, x, stage_ids)

    return wrapped
