"""Dashboard: HTTP JSON state endpoints + a minimal HTML overview.

Analog of the reference's dashboard head (reference: dashboard/head.py +
modules/{node,actor,job}/ + state_aggregator.py — theirs is an aiohttp app
with a React client; ours serves the same state JSON straight from the
head tables, with a single-page plain-HTML overview).

Endpoints: /api/cluster /api/nodes /api/actors /api/tasks /api/pgs
/api/metrics /api/timeline ; / renders the overview.
"""

from __future__ import annotations

import json
from typing import Optional


class DashboardServer:
    """Actor hosting the aiohttp app (one per cluster, like the reference's
    dashboard head process)."""

    def __init__(self, port: int):
        self.port = port

    async def start(self) -> str:
        from aiohttp import web

        import ray_tpu
        from ray_tpu._private import profiler as profiler_mod
        from ray_tpu.experimental.state import (
            list_actors,
            list_nodes,
            list_placement_groups,
            list_tasks,
        )
        from ray_tpu.util import metrics as metrics_mod

        # the dashboard's serving thread profiles under its own role, and
        # registers the same SIGUSR1 dump as every other long-lived
        # process (re-registration on the host worker is harmless)
        profiler_mod.set_thread_role("dashboard")
        profiler_mod.install_sigusr1()

        def _json(data):
            return web.json_response(data)

        async def _off(fn, *args):
            # every dashboard read is a sync head RPC round trip: run it
            # off-loop so one wedged head can't stall the whole http loop
            # for rpc_timeout_s (graftsan GS001)
            import asyncio as _aio

            return await _aio.get_running_loop().run_in_executor(None, fn, *args)

        async def api_cluster(request):
            return _json(
                {
                    "resources_total": await _off(ray_tpu.cluster_resources),
                    "resources_available": await _off(ray_tpu.available_resources),
                }
            )

        async def api_nodes(request):
            return _json(await _off(list_nodes))

        async def api_actors(request):
            return _json(await _off(list_actors))

        async def api_tasks(request):
            return _json(await _off(list_tasks))

        async def api_pgs(request):
            return _json(await _off(list_placement_groups))

        async def api_metrics(request):
            return web.Response(text=await _off(metrics_mod.prometheus_text))

        async def api_timeline(request):
            return _json(await _off(ray_tpu.timeline))

        async def api_task_summary(request):
            """Flight-recorder per-phase latency summary (p50/p95/max per
            task name); ?records=N appends the N most recent raw records;
            ?what=serve|train|memory selects a workload plane."""
            from ray_tpu.experimental.state import summarize_workloads

            try:
                limit = int(request.query.get("records", 0))
            except ValueError:
                limit = 0
            what = request.query.get("what", "tasks")
            try:
                return _json(summarize_workloads(what, limit=limit))
            except Exception as e:  # noqa: BLE001 — unknown kind etc.
                return web.json_response({"error": str(e)}, status=400)

        async def api_slo(request):
            """SLO watchdog verdicts + declared specs (the policy surface
            autoscaling/preemption will consume)."""
            from ray_tpu.experimental.state import slo_status

            return _json(slo_status())

        async def api_profile(request):
            """Sampling-profiler surface: ?op=status (armed state +
            per-(role,node) sample aggregates) or ?op=collect (the folded
            stacks themselves).  Arm/disarm stay on `ray-tpu profile` /
            util.profile_api — the dashboard is read-only."""
            import asyncio as _aio

            from ray_tpu.experimental.state.api import profile_info

            op = request.query.get("op", "status")
            if op not in ("status", "collect"):
                return web.json_response(
                    {"error": f"unknown op {op!r} (status|collect)"},
                    status=400,
                )
            # the control RPC blocks on a head round trip: keep the http
            # loop live
            reply = await _aio.get_running_loop().run_in_executor(
                None, profile_info, op
            )
            return _json(reply)

        async def api_logs(request):
            """Entity-addressed log retrieval (?actor=|?task=|?replica=|
            ?job=|?node=|?worker=ID, &tail=N, &grep=PAT) through the
            head's LOG_FETCH resolution; ?errors=1 returns the
            signature-deduped error aggregation instead."""
            from ray_tpu._private import worker as worker_mod
            from ray_tpu.experimental.state import summarize_errors

            if request.query.get("errors"):
                return _json(await _off(summarize_errors))
            kind = None
            ident = ""
            for k in ("actor", "task", "replica", "job", "node", "worker"):
                v = request.query.get(k)
                if v:
                    kind, ident = k, v
                    break
            if kind is None:
                return web.json_response(
                    {
                        "error": "pick one of ?actor=|?task=|?replica=|"
                        "?job=|?node=|?worker=ID (or ?errors=1)"
                    },
                    status=400,
                )
            try:
                tail = int(request.query.get("tail", 100))
            except ValueError:
                tail = 100

            def _fetch():
                return worker_mod._require_connected().fetch_log(
                    {
                        "kind": kind,
                        "id": ident,
                        "tail": tail,
                        "grep": request.query.get("grep") or None,
                    }
                )

            reply = await _off(_fetch)
            if not reply.get("ok"):
                return web.json_response(
                    {"error": reply.get("error", "log fetch failed")}, status=404
                )
            return _json(reply)

        async def api_events(request):
            from ray_tpu.experimental.state.api import list_cluster_events

            return _json(await _off(list_cluster_events))

        async def api_objects(request):
            from ray_tpu.experimental.state.api import list_objects

            return _json(await _off(list_objects))

        async def api_serve_get(request):
            """Serve application status (reference: the dashboard serve
            module backing `serve status`)."""
            from ray_tpu.serve import schema as serve_schema

            try:
                return _json(await _off(serve_schema.status))
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": str(e)}, status=500)

        async def api_serve_put(request):
            """Declarative deploy: PUT a ServeApplicationSchema JSON
            (reference: serve REST API, serve/schema.py)."""
            import asyncio as _aio

            from ray_tpu.serve import schema as serve_schema

            try:
                cfg = await request.json()
            except Exception:
                return web.json_response({"error": "invalid JSON"}, status=400)
            try:
                # apply() blocks on actor round trips: keep the http loop live
                out = await _aio.get_running_loop().run_in_executor(
                    None, serve_schema.apply, cfg
                )
                return _json(out)
            except (ValueError, ImportError, AttributeError) as e:
                return web.json_response({"error": str(e)}, status=400)
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": str(e)}, status=500)

        async def index(request):
            total = await _off(ray_tpu.cluster_resources)
            avail = await _off(ray_tpu.available_resources)
            nodes = await _off(list_nodes)
            actors = await _off(list_actors)
            rows = "".join(
                f"<tr><td>{n['node_id'][:12]}</td><td>{'alive' if n['alive'] else 'dead'}</td>"
                f"<td>{n['num_workers']}</td><td>{json.dumps(n['resources'])}</td></tr>"
                for n in nodes
            )
            res_rows = "".join(
                f"<tr><td>{k}</td><td>{avail.get(k, 0):.1f} / {v:.1f}</td></tr>"
                for k, v in sorted(total.items())
            )
            alive_actors = sum(1 for a in actors if a["state"] == "ALIVE")
            html = f"""<html><head><title>ray_tpu dashboard</title></head><body>
            <h2>ray_tpu cluster</h2>
            <h3>Resources (available / total)</h3>
            <table border=1>{res_rows}</table>
            <h3>Nodes ({len(nodes)})</h3>
            <table border=1><tr><th>id</th><th>state</th><th>workers</th><th>resources</th></tr>{rows}</table>
            <h3>Actors: {alive_actors} alive / {len(actors)} total</h3>
            <p>JSON: <a href=/api/cluster>cluster</a> <a href=/api/nodes>nodes</a>
            <a href=/api/actors>actors</a> <a href=/api/tasks>tasks</a>
            <a href=/api/pgs>pgs</a> <a href=/api/metrics>metrics</a>
            <a href=/api/timeline>timeline</a>
            <a href=/api/task_summary>task_summary</a>
            <a href=/api/slo>slo</a>
            <a href=/api/profile>profile</a>
            <a href=/api/events>events</a>
            <a href=/api/objects>objects</a>
            <a href="/api/logs?errors=1">logs</a></p>
            </body></html>"""
            return web.Response(text=html, content_type="text/html")

        app = web.Application()
        app.router.add_get("/", index)
        app.router.add_get("/api/cluster", api_cluster)
        app.router.add_get("/api/nodes", api_nodes)
        app.router.add_get("/api/actors", api_actors)
        app.router.add_get("/api/tasks", api_tasks)
        app.router.add_get("/api/pgs", api_pgs)
        app.router.add_get("/api/metrics", api_metrics)
        app.router.add_get("/api/timeline", api_timeline)
        app.router.add_get("/api/task_summary", api_task_summary)
        app.router.add_get("/api/slo", api_slo)
        app.router.add_get("/api/profile", api_profile)
        app.router.add_get("/api/logs", api_logs)
        app.router.add_get("/api/events", api_events)
        app.router.add_get("/api/objects", api_objects)
        app.router.add_get("/api/serve/applications", api_serve_get)
        app.router.add_put("/api/serve/applications", api_serve_put)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", self.port)
        await site.start()
        # report the BOUND port, not the requested one — port 0 means
        # "ephemeral" and the configured value would be a dead URL
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"


def start_dashboard(port: int = 8265) -> str:
    """Launch the dashboard actor; returns its URL
    (reference default port 8265)."""
    import ray_tpu

    cls = ray_tpu.remote(DashboardServer)
    actor = cls.options(num_cpus=0, name="_dashboard", lifetime="detached").remote(port)
    return ray_tpu.get(actor.start.remote(), timeout=120)
