"""Grafana dashboard template for the exported Prometheus metrics.

Analog of the reference's metrics module (reference:
dashboard/modules/metrics/ — ships Grafana dashboard JSON templates and
a default Prometheus scrape config pointing at the per-node agents).
``grafana_dashboard()`` emits an importable dashboard JSON covering the
metric families ray_tpu exposes (util/metrics.py + the per-node
/metrics endpoints, raylet/metrics_agent.py); ``prometheus_scrape_config``
emits the matching scrape stanza.  The dashboard CLI writes both:
``python -m ray_tpu.dashboard.metrics_templates OUTDIR``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List


def _panel(panel_id: int, title: str, expr: str, y: int, unit: str = "short") -> Dict[str, Any]:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "gridPos": {"h": 8, "w": 12, "x": (panel_id % 2) * 12, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": [{"expr": expr, "refId": "A"}],
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
    }


def grafana_dashboard() -> Dict[str, Any]:
    panels: List[Dict[str, Any]] = []
    # the metric families the per-node agents actually export
    # (raylet/metrics_agent.py _node_stats_text)
    rows = [
        ("Node CPU %", 'node_cpu_percent', "percent"),
        ("Node memory used", "node_mem_used_bytes", "bytes"),
        ("Node load (1m)", "node_load1", "short"),
        ("Object store used", "object_store_used_bytes", "bytes"),
        ("Object store capacity", "object_store_capacity_bytes", "bytes"),
        ("Objects resident", "object_store_num_objects", "short"),
        ("LRU evictions / s", "rate(object_store_evictions_total[1m])", "ops"),
        ("Store fill fraction", "object_store_used_bytes / object_store_capacity_bytes", "percentunit"),
    ]
    for i, (title, expr, unit) in enumerate(rows):
        panels.append(_panel(i + 1, title, expr, (i // 2) * 8, unit))
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-cluster",
        "schemaVersion": 39,
        "templating": {
            "list": [
                {
                    "name": "datasource",
                    "type": "datasource",
                    "query": "prometheus",
                }
            ]
        },
        "panels": panels,
        "time": {"from": "now-30m", "to": "now"},
        "refresh": "10s",
    }


def prometheus_scrape_config(metrics_addrs: List[str]) -> Dict[str, Any]:
    """Scrape stanza for every node's /metrics endpoint (the head's state
    API lists them: node labels carry metrics_addr)."""
    return {
        "scrape_configs": [
            {
                "job_name": "ray_tpu",
                "scrape_interval": "10s",
                "static_configs": [{"targets": metrics_addrs}],
            }
        ]
    }


def write_templates(outdir: str, metrics_addrs: List[str] = ()) -> List[str]:
    import os

    os.makedirs(outdir, exist_ok=True)
    paths = []
    p = os.path.join(outdir, "grafana_dashboard.json")
    with open(p, "w") as f:
        json.dump(grafana_dashboard(), f, indent=1)
    paths.append(p)
    p = os.path.join(outdir, "prometheus_scrape.json")
    with open(p, "w") as f:
        json.dump(prometheus_scrape_config(list(metrics_addrs) or ["127.0.0.1:0"]), f, indent=1)
    paths.append(p)
    return paths


if __name__ == "__main__":
    import sys

    out = sys.argv[1] if len(sys.argv) > 1 else "."
    for p in write_templates(out):
        print(p)
