from ray_tpu.dashboard.app import start_dashboard  # noqa: F401
