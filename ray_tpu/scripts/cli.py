"""CLI: ray-tpu start/stop/status/submit/memory/metrics/timeline/summary.

Analog of the reference's scripts (reference: python/ray/scripts/
scripts.py — start:532, stop:980, status, memory, timeline, submit:1466;
`ray summary tasks` from state/state_cli.py).  Invoke as
``python -m ray_tpu.scripts.cli <cmd>`` (or the ray-tpu entrypoint when
installed).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def cmd_start(args):
    if not args.head:
        print("only --head start is supported in this round; workers join via raylet", file=sys.stderr)
        return 1
    res = {}
    if args.num_cpus is not None:
        res["CPU"] = args.num_cpus
    if args.num_tpus is not None:
        res["TPU"] = args.num_tpus
    session_dir = f"/tmp/ray_tpu/cli_{int(time.time())}"
    os.makedirs(session_dir, exist_ok=True)
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu.gcs.head_main",
        "--host",
        args.host,
        "--port",
        str(args.port),
        "--session-dir",
        session_dir,
        "--resources",
        json.dumps(res),
    ]
    logf = open(os.path.join(session_dir, "head.log"), "ab")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=logf, start_new_session=True)
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith(b"PORT "):
            port = int(line.split()[1])
            with open("/tmp/ray_tpu/head_address", "w") as f:
                f.write(f"{args.host}:{port}\n{proc.pid}\n")
            print(f"head started at {args.host}:{port} (pid {proc.pid})")
            print(f"connect with: ray_tpu.init(address='{args.host}:{port}')")
            return 0
        if proc.poll() is not None:
            break
    print("head failed to start", file=sys.stderr)
    return 1


def _read_address(args):
    addr = getattr(args, "address", None)
    if addr:
        return addr
    try:
        with open("/tmp/ray_tpu/head_address") as f:
            return f.read().splitlines()[0]
    except OSError:
        print("no running head found (missing /tmp/ray_tpu/head_address)", file=sys.stderr)
        sys.exit(1)


def cmd_stop(args):
    try:
        with open("/tmp/ray_tpu/head_address") as f:
            lines = f.read().splitlines()
        pid = int(lines[1])
        os.kill(pid, 15)
        os.remove("/tmp/ray_tpu/head_address")
        print(f"stopped head (pid {pid})")
        return 0
    except (OSError, IndexError, ValueError) as e:
        print(f"stop failed: {e}", file=sys.stderr)
        return 1


def cmd_status(args):
    import ray_tpu

    ray_tpu.init(address=_read_address(args))
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print("== cluster resources ==")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):.1f}/{total[k]:.1f} available")
    print("== nodes ==")
    for n in ray_tpu.nodes():
        print(f"  {n['NodeID'][:12]} alive={n['Alive']} {n['Resources']}")
    from ray_tpu.experimental.state import list_actors

    actors = list_actors()
    alive = sum(1 for a in actors if a["state"] == "ALIVE")
    print(f"== actors == {alive} alive / {len(actors)} total")
    return 0


def cmd_memory(args):
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    ray_tpu.init(address=_read_address(args))
    cw = worker_mod._require_connected()
    store = cw.store
    print(
        f"object store: {store.used()}/{store.capacity()} bytes, "
        f"{store.num_objects()} objects, {store.evictions()} evictions"
    )
    return 0


def cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(address=_read_address(args))
    job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
    print(f"submitted {job_id}")
    if args.wait:
        status = client.wait_until_finish(job_id, timeout=args.timeout)
        print(f"{job_id}: {status}")
        print(client.get_job_logs(job_id))
        return 0 if status == "SUCCEEDED" else 1
    return 0


def cmd_metrics(args):
    import ray_tpu
    from ray_tpu.util import metrics as m

    ray_tpu.init(address=_read_address(args))
    sys.stdout.write(m.prometheus_text())
    return 0


def cmd_timeline(args):
    """Export the cluster timeline — task exec windows, flight-recorder
    per-phase sub-spans, cluster-event markers — as a chrome://tracing
    JSON file (reference: `ray timeline`, scripts.py:timeline)."""
    import ray_tpu

    ray_tpu.init(address=_read_address(args))
    out = args.output or f"/tmp/ray-tpu-timeline-{int(time.time())}.json"
    events = ray_tpu.timeline(filename=out)
    print(f"wrote {len(events)} events to {out}")
    print("open chrome://tracing and load the file to view")
    return 0


def _latency_table(rows, key_a, key_b, label_a, label_b):
    hdr = (
        f"{label_a:28s} {label_b:20s} {'count':>7s} {'p50':>10s} "
        f"{'p95':>10s} {'p99':>10s} {'max':>10s}"
    )
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(
            f"{str(r[key_a])[:28]:28s} {str(r[key_b])[:20]:20s} {r['count']:7d} "
            f"{r['p50'] * 1e3:9.2f}ms {r['p95'] * 1e3:9.2f}ms "
            f"{r.get('p99', r['p95']) * 1e3:9.2f}ms {r['max'] * 1e3:9.2f}ms"
        )


def _print_fleet_gauges(fleet: dict) -> None:
    """Serve fleet-survival block for `summary serve`: replica count,
    scale events, mid-stream failovers, drain outcomes per deployment."""
    if not fleet:
        return
    print("== serve fleet ==")
    for dep, g in sorted(fleet.items()):
        print(
            f"  {dep}: replicas={g.get('replicas', 0):.0f} "
            f"scale_out={g.get('scale_events_total:out', 0):.0f} "
            f"scale_in={g.get('scale_events_total:in', 0):.0f} "
            f"failovers={g.get('failovers_total', 0):.0f} "
            f"drained(clean={g.get('drained_total:clean', 0):.0f} "
            f"deadline={g.get('drained_total:deadline', 0):.0f})"
        )


def _print_engine_gauges(engine: dict) -> None:
    """Continuous-batching engine occupancy block shared by
    `summary serve` and `summary memory`."""
    if not engine:
        return
    print("== serve engine (continuous batching) ==")
    for dep, gauges in sorted(engine.items()):
        slots = gauges.get("slots:active", 0)
        total = gauges.get("slots:total", 0)
        pages = gauges.get("kv_pages:used", 0)
        ptotal = gauges.get("kv_pages:total", 0)
        print(
            f"  {dep}: slots={slots:.0f}/{total:.0f} "
            f"(prefill={gauges.get('slots:prefill', 0):.0f} "
            f"decode={gauges.get('slots:decode', 0):.0f}) "
            f"kv_pages={pages:.0f}/{ptotal:.0f} "
            f"queue={gauges.get('queue_depth', 0):.0f} "
            f"frag={gauges.get('page_fragmentation', 0):.2f} "
            f"tokens={gauges.get('tokens_total', 0):.0f}"
        )


def cmd_summary(args):
    """`ray-tpu summary tasks|serve|train|memory`: workload-plane latency
    and occupancy tables from the head's flight recorder."""
    import ray_tpu  # noqa: F401  (init side effect)
    from ray_tpu.experimental.state import summarize_workloads

    ray_tpu.init(address=_read_address(args))
    reply = summarize_workloads(args.what)
    if args.what == "memory":
        print("== shm stores (per node) ==")
        for nid, st in reply.get("nodes", {}).items():
            used = st.get("used", 0)
            cap = st.get("capacity", 0)
            print(
                f"  {nid[:12]} alive={st.get('alive')} "
                f"used={used:.0f}/{cap:.0f} bytes "
                f"objects={st.get('objects', 0):.0f} "
                f"evictions={st.get('evictions', 0):.0f}"
            )
        obj = reply.get("objects", {})
        print(
            f"== objects == total={obj.get('total', 0)} "
            f"pinned={obj.get('pinned', 0)} spilled={obj.get('spilled', 0)} "
            f"lineage={obj.get('lineage', 0)} by_state={obj.get('by_state')}"
        )
        for owner, st in sorted(obj.get("by_owner", {}).items()):
            print(f"  owner {owner}: {st['count']} objects, {st['bytes']} bytes")
        chans = reply.get("dag_channels", {})
        if chans:
            print("== dag channels ==")
            for key, st in sorted(chans.items()):
                print(
                    f"  {key[:40]:40s} occupancy={st.get('occupancy')}/"
                    f"{st.get('slots')} slots"
                )
        _print_engine_gauges(reply.get("serve_engine", {}))
        return 0
    if args.what == "head":
        print(
            f"== head == incarnation={reply.get('incarnation')} "
            f"restarts={reply.get('restarts_total')} "
            f"node={str(reply.get('head_node_id', ''))[:12]} "
            f"recovering={reply.get('recovering')}"
        )
        lr = reply.get("last_recovery")
        if lr:
            att = lr.get("reattached", {})
            reaped = lr.get("reaped", {})
            resub = lr.get("resubmits", {})
            print(
                f"  last recovery: {lr.get('duration_s', 0):.2f}s at "
                f"{time.strftime('%H:%M:%S', time.localtime(lr.get('at', 0)))} "
                f"(incarnation {lr.get('incarnation')})"
            )
            print(
                f"  reattached: {att.get('nodes', 0)} nodes, "
                f"{att.get('workers', 0)} workers, {att.get('drivers', 0)} "
                f"drivers, {att.get('actors', 0)} actors, "
                f"{att.get('tasks', 0)} running tasks, "
                f"{att.get('leases', 0)} leases"
            )
            print(
                f"  reaped: {reaped.get('actors', 0)} actors, "
                f"{reaped.get('owners', 0)} orphaned owners, "
                f"{reaped.get('locations', 0)} stale locations, "
                f"{reaped.get('spills', 0)} stale spills; resubmits "
                f"{resub.get('deduped', 0)}/{resub.get('received', 0)} deduped"
            )
        else:
            print("  no recovery this incarnation")
        return 0
    if args.what == "preemptions":
        counts = reply.get("counts", {})
        print(
            f"== preemptions == total={reply.get('total', 0)} "
            f"parked_actors={len(reply.get('parked', []))} "
            f"slo_hold={reply.get('slo_hold')}"
        )
        for key, n in sorted(counts.items()):
            print(f"  {key}: {n:.0f}")
        for rec in reply.get("preemptions", [])[-50:]:
            print(
                f"  {time.strftime('%H:%M:%S', time.localtime(rec['ts']))} "
                f"{rec['kind']:12s} band={rec['band']} -> "
                f"req_band={rec['requester_band']} "
                f"{rec.get('name') or rec.get('victim', '')} "
                f"{rec.get('reason', '')}"
            )
        return 0
    if args.what == "errors":
        counts = reply.get("counts", {})
        print(
            f"== errors == {reply.get('distinct', 0)} distinct signatures, "
            f"{reply.get('total', 0)} records in the ring"
        )
        for key, n in sorted(counts.items()):
            print(f"  {key}: {n:.0f}")
        for row in reply.get("errors", []):
            first = time.strftime("%H:%M:%S", time.localtime(row.get("first_ts", 0)))
            last = time.strftime("%H:%M:%S", time.localtime(row.get("last_ts", 0)))
            print(
                f"  x{row.get('count', 0):<5d} [{row.get('kind')}] "
                f"{row.get('exc_type', '?')} in {row.get('name', '?')} "
                f"(first {first}, last {last})"
            )
            msg = str(row.get("message", "")).splitlines()
            if msg:
                print(f"         {msg[0][:160]}")
        return 0
    rows = reply.get("summary", [])
    if not rows:
        print(
            f"no {args.what} flight records yet "
            "(is RAY_TPU_TASK_EVENTS=0, or nothing run?)"
        )
        return 0
    if args.what == "serve":
        _latency_table(rows, "deployment", "stage", "deployment", "stage")
        for dep, p in sorted(reply.get("ttft", {}).items()):
            print(
                f"TTFT {dep}: p50={p['p50'] * 1e3:.1f}ms "
                f"p99={p['p99'] * 1e3:.1f}ms (n={p['count']})"
            )
        for dep, p in sorted(reply.get("tpot", {}).items()):
            print(
                f"TPOT {dep}: p50={p['p50'] * 1e3:.2f}ms "
                f"p99={p['p99'] * 1e3:.2f}ms (n={p['count']})"
            )
        _print_engine_gauges(reply.get("engine", {}))
        _print_fleet_gauges(reply.get("fleet", {}))
    elif args.what == "train":
        _latency_table(rows, "run", "phase", "run", "phase")
        for run, st in sorted(reply.get("runs", {}).items()):
            mfu = st.get("mfu")
            print(
                f"run {run}: steps={st.get('steps', 0):.0f} "
                f"p50={st.get('p50_s', 0) * 1e3:.1f}ms "
                f"p99={st.get('p99_s', 0) * 1e3:.1f}ms "
                f"jitter={st.get('jitter_pct', 0):.1f}%"
                + (f" mfu={mfu:.3f}" if mfu is not None else "")
            )
    else:
        _latency_table(rows, "name", "phase", "task", "phase")
    print(f"({reply.get('total_records', 0)} records joined at the head)")
    return 0


def _write_folded(stacks, out_path):
    """Write a profile_api.collect() result as ONE merged collapsed-stack
    file (role;pid;thread roots keep per-process flames separable) and
    print the per-bucket totals + each bucket's hottest leaf frames."""
    from ray_tpu.util import profile_api

    with open(out_path, "w") as f:
        f.write(profile_api.folded_text(stacks))
    total = 0
    for bucket in sorted(stacks):
        per = stacks[bucket]
        n = sum(per.values())
        total += n
        top = sorted(per.items(), key=lambda kv: -kv[1])[:3]
        print(f"  {bucket:24s} {n:7d} samples, {len(per)} stacks")
        for folded, count in top:
            leaf = folded.rsplit(";", 1)[-1]
            print(f"      {count:6d}  {leaf}")
    print(f"wrote {total} samples to {out_path}")
    print("render with: flamegraph.pl " + out_path + " > profile.svg")


def cmd_profile(args):
    """`ray-tpu profile start|stop|snapshot|status`: the cluster-wide
    wall-clock sampling profiler (see util/profile_api.py)."""
    import ray_tpu
    from ray_tpu.util import profile_api

    ray_tpu.init(address=_read_address(args))
    roles = args.role or None
    if args.action == "start":
        st = profile_api.start(hz=args.hz, roles=roles, deep=args.deep)
        print(
            f"profiler armed (hz={st.get('ctrl', {}).get('hz', args.hz)}, "
            f"roles={roles or 'all'}, deep={args.deep})"
        )
        return 0
    if args.action == "status":
        st = profile_api.status()
        print(f"armed: {st.get('armed')}  ctrl: {st.get('ctrl')}")
        for bucket, agg in sorted((st.get("aggregate") or {}).items()):
            print(
                f"  {bucket:24s} samples={agg.get('samples', 0):7d} "
                f"stacks={agg.get('distinct_stacks', 0):5d} "
                f"overhead={agg.get('overhead_ratio', 0.0):.2%}"
            )
        return 0
    out = args.out or f"/tmp/ray-tpu-profile-{int(time.time())}.folded"
    if args.action == "stop":
        # disarm FIRST: the disarm-triggered final flush carries each
        # process's last partial window; collecting before it lands
        # would drop up to profiler_flush_period_s of samples
        profile_api.stop()
        time.sleep(1.0)
        stacks = profile_api.collect()
    else:  # snapshot
        stacks = profile_api.snapshot(
            duration=args.duration, hz=args.hz, roles=roles, deep=args.deep
        )
    if not stacks:
        print(
            "no samples collected (is the cluster idle, or was every "
            "process started with RAY_TPU_PROFILER=0?)"
        )
        return 1
    _write_folded(stacks, out)
    return 0


def cmd_stacks(args):
    """`ray-tpu stacks`: one-shot cluster-wide native stack dump over
    PROFILE_CTRL — every profiler-aware process ships all-thread
    tracebacks to the head."""
    import ray_tpu
    from ray_tpu.util import profile_api

    ray_tpu.init(address=_read_address(args))
    dumps = profile_api.stack_dumps()
    if not dumps:
        print("no stack dumps arrived (RAY_TPU_PROFILER=0 everywhere?)")
        return 1
    for d in dumps:
        print(f"##### {d.get('role')} pid={d.get('pid')} node={d.get('node')}")
        print(d.get("text", ""))
        print()
    print(f"({len(dumps)} process dumps)")
    return 0


def cmd_logs(args):
    """`ray-tpu logs --actor|--task|--replica|--job|--node|--worker ID`:
    pull-based log retrieval through the head's LOG_FETCH resolution —
    tail-N by default, ``--follow`` switches to cursor polling."""
    import ray_tpu
    from ray_tpu._private import log_plane
    from ray_tpu._private import worker as worker_mod

    ray_tpu.init(address=_read_address(args))
    cw = worker_mod._require_connected()
    kind = None
    ident = ""
    for k in ("actor", "task", "replica", "job", "node", "worker"):
        v = getattr(args, k, None)
        if v:
            kind, ident = k, v
            break
    if kind is None:
        print(
            "pick an entity: --actor/--task/--replica/--job/--node/--worker ID",
            file=sys.stderr,
        )
        return 2

    def _print(records):
        for rec in records:
            prefix = log_plane.record_prefix(rec, rec.get("src", ""))
            print(f"{prefix} {rec.get('msg', '')}", flush=True)

    reply = cw.fetch_log(
        {"kind": kind, "id": ident, "tail": args.tail, "grep": args.grep}
    )
    if not reply.get("ok"):
        print(f"log fetch failed: {reply.get('error')}", file=sys.stderr)
        return 1
    _print(reply.get("records") or [])
    if not args.follow:
        return 0
    cursor = reply.get("cursor") or {}
    try:
        while True:
            time.sleep(1.0)
            reply = cw.fetch_log(
                {"kind": kind, "id": ident, "cursor": cursor, "grep": args.grep}
            )
            if not reply.get("ok"):
                print(f"log follow failed: {reply.get('error')}", file=sys.stderr)
                return 1
            _print(reply.get("records") or [])
            cursor = reply.get("cursor") or cursor
    except KeyboardInterrupt:
        return 0


def cmd_slo(args):
    """`ray-tpu slo`: the watchdog's verdict per declared SLO."""
    import ray_tpu
    from ray_tpu.experimental.state import slo_status

    ray_tpu.init(address=_read_address(args))
    reply = slo_status()
    slos = reply.get("slos", [])
    if not slos:
        print(
            "no SLOs declared (ray_tpu.util.slo_api.set_slos([...]) or "
            "RAY_TPU_SLO_SPECS)"
        )
        return 0
    hdr = (
        f"{'slo':28s} {'ok':>4s} {'value':>12s} {'threshold':>12s} "
        f"{'burn':>8s} {'window':>8s} {'samples':>8s}"
    )
    print(hdr)
    print("-" * len(hdr))
    breached = 0
    for s in slos:
        ok = bool(s.get("ok"))
        breached += 0 if ok else 1
        val = s.get("value")
        print(
            f"{s['name'][:28]:28s} {'OK' if ok else 'FAIL':>4s} "
            f"{(f'{val:.4g}' if val is not None else '-'):>12s} "
            f"{s.get('threshold', 0):>12.4g} "
            f"{s.get('burn_rate', 0):>8.2f} "
            f"{s.get('window_s', 0):>7.0f}s "
            f"{s.get('samples', 0):>8d}"
        )
    return 1 if breached else 0


def main():
    parser = argparse.ArgumentParser(prog="ray-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop the head")
    p.set_defaults(fn=cmd_stop)

    for name, fn in (("status", cmd_status), ("memory", cmd_memory), ("metrics", cmd_metrics)):
        p = sub.add_parser(name)
        p.add_argument("--address", default=None)
        p.set_defaults(fn=fn)

    p = sub.add_parser("timeline", help="export a chrome://tracing JSON of recent tasks")
    p.add_argument("--address", default=None)
    p.add_argument("--output", "-o", default=None)
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("summary", help="workload summaries from the flight recorder")
    p.add_argument(
        "what",
        choices=["tasks", "serve", "train", "memory", "preemptions", "head", "errors"],
    )
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser(
        "logs", help="fetch logs by entity (worker/actor/task/replica/job/node)"
    )
    p.add_argument("--address", default=None)
    p.add_argument("--actor", default=None, help="actor id (hex, prefix ok)")
    p.add_argument("--task", default=None, help="task id (hex, prefix ok)")
    p.add_argument(
        "--replica", default=None, help="serve replica as deployment#index"
    )
    p.add_argument("--job", default=None, help="job id (hex)")
    p.add_argument("--node", default=None, help="node id (hex, prefix ok)")
    p.add_argument("--worker", default=None, help="worker id (hex, prefix ok)")
    p.add_argument("--tail", type=int, default=100, help="last N lines (default 100)")
    p.add_argument("--follow", "-f", action="store_true", help="keep polling for new lines")
    p.add_argument("--grep", default=None, help="only lines matching this regex")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("slo", help="SLO watchdog verdicts (exit 1 on a breach)")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser(
        "profile",
        help="cluster-wide sampling profiler (flamegraph collapsed stacks)",
    )
    p.add_argument("action", choices=["start", "stop", "snapshot", "status"])
    p.add_argument("--address", default=None)
    p.add_argument("--duration", type=float, default=2.0, help="snapshot window (s)")
    p.add_argument("--hz", type=int, default=None, help="sampling rate (default: profiler_hz config)")
    p.add_argument(
        "--role",
        action="append",
        default=None,
        help="only sample these roles (head/raylet/worker/driver/engine/dashboard); repeatable",
    )
    p.add_argument(
        "--deep",
        action="store_true",
        help="also collect jax.profiler device traces on RAY_TPU_PROFILER_DEVICE=1 workers",
    )
    p.add_argument("--out", "-o", default=None, help="collapsed-stack output file")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser(
        "stacks", help="one-shot cluster-wide native stack dump (all threads)"
    )
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_stacks)

    p = sub.add_parser("submit", help="submit a job entrypoint command")
    p.add_argument("--address", default=None)
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600)
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    args = parser.parse_args()
    sys.exit(args.fn(args))


if __name__ == "__main__":
    main()
