"""Sharded GCS namespaces: KV, object-locate, and actor-directory reads
served off the head event loop.

The head loop is the task-dispatch critical path (ROADMAP item 1 /
PAPERS.md §2: dispatch latency is the scarce resource).  Before this
module, every KV get, metrics scrape, object-locate wait, and actor
lookup serialized behind task dispatch on that one loop.  Now:

- ``ShardedKV`` is the cluster KV table itself: a thread-safe mapping
  partitioned into per-shard dicts with per-shard locks, plus a global
  waiter registry (``kv_get(wait=True)`` futures fire on THEIR owning
  event loop via call_soon_threadsafe, whichever thread performs the
  put).  The head server holds one instance as ``self.kv`` — all of its
  internal reads/writes go through the same store the shard servers
  serve, so there is exactly one source of truth.

- ``ObjectMirror`` / ``ActorMirror`` are read replicas of the head's
  object directory (seal state only — locations and transfers stay
  authoritative on the head) and actor table.  The head writes through
  on every transition (a dict store + possible waiter wake, O(1)); the
  shard listeners serve ``WAIT_OBJECT`` (batch and locate forms) and
  ``GET_ACTOR`` / read-only ``ACTOR_STATE`` from them.

- ``GcsShardServer`` runs N threads, each with its OWN asyncio loop and
  TCP listener (reference analog: the multi-shard GCS deployments of
  Ray 2.x whitepapers; here threads-with-own-loops, since the data is
  lock-partitioned in one process).  Clients learn the shard addresses
  at registration and route shardable message types there
  (core_worker.request), falling back to the head connection — the head
  keeps every handler, so shards are purely an offload.
"""

from __future__ import annotations

import asyncio
import logging
import threading

import zlib
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util.lockwitness import named_lock

logger = logging.getLogger("ray_tpu.gcs.shards")

# object mirror states (match gcs/server.py PENDING/SEALED/ERRORED)
PENDING, SEALED, ERRORED = 0, 1, 2


class ShardedKV:
    """Thread-safe cluster KV table partitioned into lock shards.

    Implements the mapping surface gcs/server.py uses (``[]``, ``get``,
    ``pop``, ``in``, iteration, ``items``/``keys``) — iteration returns a
    snapshot, so handler code can await mid-scan without tripping over
    concurrent shard writes."""

    def __init__(self, nshards: int = 4):
        n = max(1, int(nshards))
        self._n = n
        self._shards: List[Dict[str, bytes]] = [dict() for _ in range(n)]
        self._locks = [named_lock(f"ShardedKV._locks[{i}]") for i in range(n)]
        # key -> [(loop, future)]: kv_get(wait=True) waiters, fired by
        # whichever thread lands the put (on the waiter's own loop)
        self._waiters: Dict[str, List[Tuple[asyncio.AbstractEventLoop, asyncio.Future]]] = {}
        self._wlock = named_lock("ShardedKV._wlock")

    def _i(self, key: str) -> int:
        return zlib.crc32(key.encode()) % self._n

    # ---------------------------------------------------- mapping surface

    def __getitem__(self, key: str) -> bytes:
        i = self._i(key)
        with self._locks[i]:
            return self._shards[i][key]

    def __setitem__(self, key: str, value: bytes):
        i = self._i(key)
        with self._locks[i]:
            self._shards[i][key] = value

    def __delitem__(self, key: str):
        i = self._i(key)
        with self._locks[i]:
            del self._shards[i][key]

    def __contains__(self, key: str) -> bool:
        i = self._i(key)
        with self._locks[i]:
            return key in self._shards[i]

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    def get(self, key: str, default=None):
        i = self._i(key)
        with self._locks[i]:
            return self._shards[i].get(key, default)

    def pop(self, key: str, *default):
        i = self._i(key)
        with self._locks[i]:
            return self._shards[i].pop(key, *default)

    def keys(self) -> List[str]:
        out: List[str] = []
        for i in range(self._n):
            with self._locks[i]:
                out.extend(self._shards[i].keys())
        return out

    def items(self) -> List[Tuple[str, bytes]]:
        out: List[Tuple[str, bytes]] = []
        for i in range(self._n):
            with self._locks[i]:
                out.extend(self._shards[i].items())
        return out

    def update(self, other):
        for k, v in (other.items() if hasattr(other, "items") else other):
            self[k] = v

    # ------------------------------------------------------- put + waiters

    def put_notify(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        """The KV_PUT body: store (respecting overwrite=False) and fire
        any registered kv-wait futures on their own loops.  Returns
        whether the value was added."""
        i = self._i(key)
        with self._locks[i]:
            if not overwrite and key in self._shards[i]:
                return False
            self._shards[i][key] = value
        with self._wlock:
            waiters = self._waiters.pop(key, [])
        for loop, fut in waiters:
            try:
                loop.call_soon_threadsafe(self._fire, fut)
            except RuntimeError:
                pass  # waiter's loop already closed
        return True

    @staticmethod
    def _fire(fut: asyncio.Future):
        if not fut.done():
            fut.set_result(True)

    def register_waiter(self, key: str) -> Optional[asyncio.Future]:
        """Register a kv-wait future on the CALLING loop; returns None if
        the key already exists (nothing to wait for)."""
        loop = asyncio.get_running_loop()
        with self._wlock:
            # check under the waiter lock so a concurrent put_notify can't
            # land between our existence check and the registration
            if key in self:
                return None
            fut = loop.create_future()
            self._waiters.setdefault(key, []).append((loop, fut))
        return fut

    def unregister_waiter(self, key: str, fut: asyncio.Future):
        with self._wlock:
            lst = self._waiters.get(key)
            if lst is None:
                return
            self._waiters[key] = [(l, f) for (l, f) in lst if f is not fut]
            if not self._waiters[key]:
                self._waiters.pop(key, None)


class ObjectMirror:
    """Seal-state read replica of the head's object directory, with its
    own waiter registry so WAIT_OBJECT can be served from any shard loop
    (or the head loop) and woken by the head's write-through."""

    def __init__(self):
        self._state: Dict[bytes, Tuple[int, Optional[str]]] = {}
        self._waiters: Dict[bytes, List[Tuple[asyncio.AbstractEventLoop, asyncio.Future]]] = {}
        self._lock = named_lock("ObjectMirror._lock")

    def state(self, oid: bytes) -> Tuple[int, Optional[str]]:
        with self._lock:
            return self._state.get(oid, (PENDING, None))

    def _transition(self, oid: bytes, st: Tuple[int, Optional[str]], wake: bool):
        with self._lock:
            self._state[oid] = st
            waiters = self._waiters.pop(oid, []) if wake else []
        for loop, fut in waiters:
            try:
                loop.call_soon_threadsafe(ShardedKV._fire, fut)
            except RuntimeError:
                pass

    def seal(self, oid: bytes):
        self._transition(bytes(oid), (SEALED, None), wake=True)

    def error(self, oid: bytes, msg: str):
        self._transition(bytes(oid), (ERRORED, msg), wake=True)

    def reset(self, oid: bytes):
        """Back to PENDING (reconstruction re-running the producer)."""
        with self._lock:
            self._state[bytes(oid)] = (PENDING, None)

    def drop(self, oid: bytes):
        with self._lock:
            self._state.pop(bytes(oid), None)

    def register_waiter(self, oid: bytes) -> Optional[asyncio.Future]:
        """None if already non-pending (check-then-register is atomic)."""
        loop = asyncio.get_running_loop()
        with self._lock:
            if self._state.get(bytes(oid), (PENDING, None))[0] != PENDING:
                return None
            fut = loop.create_future()
            self._waiters.setdefault(bytes(oid), []).append((loop, fut))
        return fut

    def unregister_waiter(self, oid: bytes, fut: asyncio.Future):
        with self._lock:
            lst = self._waiters.get(bytes(oid))
            if lst is None:
                return
            kept = [(l, f) for (l, f) in lst if f is not fut]
            if kept:
                self._waiters[bytes(oid)] = kept
            else:
                self._waiters.pop(bytes(oid), None)


class ActorMirror:
    """Read replica of the actor directory: GET_ACTOR / read-only
    ACTOR_STATE served without touching the head loop."""

    def __init__(self):
        self._actors: Dict[bytes, dict] = {}
        self._named: Dict[Tuple[str, str], bytes] = {}
        self._lock = named_lock("ActorMirror._lock")

    def upsert(self, actor_id: bytes, **fields):
        with self._lock:
            slot = self._actors.setdefault(bytes(actor_id), {})
            slot.update(fields)
            name = slot.get("name")
            if name:
                self._named[(slot.get("namespace", ""), name)] = bytes(actor_id)

    def drop_name(self, namespace: str, name: str):
        with self._lock:
            self._named.pop((namespace, name), None)

    def lookup(self, actor_id: Optional[bytes], namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            aid = bytes(actor_id) if actor_id else self._named.get((namespace, name))
            if aid is None:
                return None
            info = self._actors.get(aid)
            return dict(info, actor_id=aid) if info is not None else None


class GcsShardServer:
    """N shard threads, each with its own event loop and TCP listener,
    serving the read planes above.  Start from the head process; the
    returned addresses ride the REGISTER_* replies to clients."""

    def __init__(
        self,
        kv: ShardedKV,
        objects: ObjectMirror,
        actors: ActorMirror,
        host: str = "127.0.0.1",
        wal_cb=None,
        dirty_cb=None,
    ):
        self.kv = kv
        self.objects = objects
        self.actors = actors
        self.host = host
        # thread-safe callbacks into the head's persistence plumbing;
        # the head marshals onto its own loop internally
        self._wal_cb = wal_cb or (lambda *a: None)
        self._dirty_cb = dirty_cb or (lambda: None)
        self._threads: List[threading.Thread] = []
        self._loops: List[asyncio.AbstractEventLoop] = []
        self.addrs: List[str] = []
        self._stopping = False

    # ------------------------------------------------------------- lifecycle

    def start(self, nshards: int, advertise: Optional[str] = None) -> List[str]:
        for i in range(max(0, int(nshards))):
            ready = threading.Event()
            holder: Dict[str, Any] = {}
            t = threading.Thread(
                target=self._shard_thread,
                args=(i, ready, holder),
                name=f"gcs-shard-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
            ready.wait(10)
            port = holder.get("port")
            if port:
                self.addrs.append(f"{advertise or self.host}:{port}")
                self._loops.append(holder["loop"])
        return self.addrs

    def stop(self):
        self._stopping = True
        for loop in self._loops:
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass

    def _shard_thread(self, idx: int, ready: threading.Event, holder: dict):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def _boot():
            bind = "0.0.0.0" if self.host in ("0.0.0.0", "") else self.host
            server = await asyncio.start_server(self._on_connection, bind, 0)
            holder["port"] = server.sockets[0].getsockname()[1]
            holder["loop"] = loop

        try:
            loop.run_until_complete(_boot())
        except OSError:
            logger.exception("gcs shard %d failed to bind; running without it", idx)
            ready.set()
            return
        ready.set()
        try:
            loop.run_forever()
        finally:
            loop.close()

    # --------------------------------------------------------------- serving

    async def _on_connection(self, reader, writer):
        from ray_tpu._private.protocol import Connection

        conn = Connection(reader, writer)
        try:
            while not self._stopping:
                msg_type, rid, payload = await conn.read_frame()
                asyncio.get_running_loop().create_task(
                    self._handle(conn, msg_type, rid, payload)
                )
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            conn.close()

    async def _handle(self, conn, msg_type: int, rid: int, payload: dict):
        from ray_tpu._private.protocol import MsgType

        try:
            if msg_type == MsgType.KV_PUT:
                result = await self._h_kv_put(payload)
            elif msg_type == MsgType.KV_GET:
                result = await self._h_kv_get(payload)
            elif msg_type == MsgType.KV_DEL:
                result = self._h_kv_del(payload)
            elif msg_type == MsgType.KV_KEYS:
                result = self._h_kv_keys(payload)
            elif msg_type == MsgType.KV_EXISTS:
                result = {"exists": payload["key"] in self.kv}
            elif msg_type == MsgType.WAIT_OBJECT:
                result = await self._h_wait_object(payload)
            elif msg_type == MsgType.GET_ACTOR:
                result = self._h_get_actor(payload)
            elif msg_type == MsgType.ACTOR_STATE:
                result = self._h_actor_state(payload)
            elif msg_type == MsgType.HEARTBEAT:
                result = {"ok": True}
            else:
                raise ValueError(f"message type {msg_type} is not shard-servable")
            if rid:
                await conn.reply(rid, result or {})
        except Exception as e:  # noqa: BLE001
            logger.exception("shard handler error for msg %s", msg_type)
            if rid:
                try:
                    await conn.reply(rid, {}, error=f"{type(e).__name__}: {e}")
                except Exception:  # graftlint: disable=silent-except -- error already logged; reply transport dead
                    pass

    # ------------------------------------------------------------------- KV

    async def _h_kv_put(self, p) -> dict:
        key = p["key"]
        added = self.kv.put_notify(key, p["value"], p.get("overwrite", True))
        if added:
            self._wal_cb("kv", key, p["value"])
            self._dirty_cb()
        return {"added": added}

    async def _h_kv_get(self, p) -> dict:
        from ray_tpu._private.config import RayConfig

        key = p["key"]
        if p.get("wait") and key not in self.kv:
            timeout = p.get("timeout") or RayConfig.collective_rendezvous_timeout_s
            fut = self.kv.register_waiter(key)
            if fut is not None:
                try:
                    await asyncio.wait_for(fut, timeout)
                except asyncio.TimeoutError:
                    return {"found": False}
                finally:
                    self.kv.unregister_waiter(key, fut)
        v = self.kv.get(key)
        return {"found": v is not None, "value": v if v is not None else b""}

    def _h_kv_del(self, p) -> dict:
        n = 0
        if p.get("prefix"):
            for k in [k for k in self.kv.keys() if k.startswith(p["key"])]:
                if self.kv.pop(k, None) is not None:
                    self._wal_cb("kv", k, None)
                    n += 1
        elif self.kv.pop(p["key"], None) is not None:
            self._wal_cb("kv", p["key"], None)
            n = 1
        if n:
            self._dirty_cb()
        return {"deleted": n}

    def _h_kv_keys(self, p) -> dict:
        pref = p.get("prefix", "")
        keys = [k for k in self.kv.keys() if k.startswith(pref)]
        if p.get("values"):
            vals = {}
            for k in keys:
                v = self.kv.get(k)
                if v is not None:
                    vals[k] = v
            return {"keys": keys, "values": vals}
        return {"keys": keys}

    # --------------------------------------------------------------- objects

    async def _h_wait_object(self, p) -> dict:
        """Seal-state waits only: the batch form and the single form
        without a destination node.  Transfer-triggering waits (node_id
        set) are routed to the head by the client."""
        if "object_ids" in p:
            return await self._wait_batch(p)
        import time

        oid = bytes(p["object_id"])
        timeout = p.get("timeout")
        deadline = time.time() + timeout if timeout is not None else None
        while True:
            st, err = self.objects.state(oid)
            if st == ERRORED:
                return {"state": "error", "error": err}
            if st == SEALED:
                return {"state": "sealed"}
            fut = self.objects.register_waiter(oid)
            if fut is None:
                continue  # sealed between check and register
            rem = None if deadline is None else max(0.001, deadline - time.time())
            try:
                await asyncio.wait_for(fut, rem)
            except asyncio.TimeoutError:
                return {"state": "timeout"}
            finally:
                self.objects.unregister_waiter(oid, fut)

    async def _wait_batch(self, p) -> dict:
        import time

        oids = [bytes(o) for o in p["object_ids"]]
        want = min(p.get("num_ready", len(oids)), len(oids))
        timeout = p.get("timeout")
        deadline = time.time() + timeout if timeout is not None else None
        registered: List[Tuple[bytes, asyncio.Future]] = []
        ev = asyncio.Event()
        state = {"done": 0}

        def _on_done(_f):
            state["done"] += 1
            ev.set()

        try:
            if deadline is None or time.time() < deadline:
                for o in oids:
                    fut = self.objects.register_waiter(o)
                    if fut is not None:
                        fut.add_done_callback(_on_done)
                        registered.append((o, fut))
                # exact ready count AT registration time: every oid that
                # declined a waiter is non-pending.  (A separate pre-count
                # plus counting declines again DOUBLE-counts ready oids —
                # the loop below then exits early and the caller turns the
                # short ready-set into a spurious GetTimeoutError.)
                n_ready = len(oids) - len(registered)
                while n_ready + state["done"] < want and state["done"] < len(registered):
                    if deadline is not None and time.time() >= deadline:
                        break
                    rem = None if deadline is None else max(0.001, deadline - time.time())
                    ev.clear()
                    try:
                        await asyncio.wait_for(ev.wait(), rem)
                    except asyncio.TimeoutError:
                        break
            return {
                "ready": [o for o in oids if self.objects.state(o)[0] != PENDING]
            }
        finally:
            for o, f in registered:
                if not f.done():
                    f.remove_done_callback(_on_done)
                    f.cancel()
                self.objects.unregister_waiter(o, f)

    # ---------------------------------------------------------------- actors

    def _h_get_actor(self, p) -> dict:
        info = self.actors.lookup(
            p.get("actor_id"), p.get("namespace", ""), p.get("name", "")
        )
        if info is None or info.get("creation_spec") is None:
            return {"found": False}
        return {
            "found": info.get("state") != "DEAD",
            "actor_id": info["actor_id"],
            "state": info.get("state", "UNKNOWN"),
            "creation_spec": info["creation_spec"],
            "direct_addr": info.get("direct_addr", ""),
        }

    def _h_actor_state(self, p) -> dict:
        info = self.actors.lookup(p.get("actor_id"), "", "")
        if info is None:
            return {"state": "UNKNOWN"}
        return {
            "state": info.get("state", "UNKNOWN"),
            "death_cause": info.get("death_cause", ""),
            "direct_addr": info.get("direct_addr", ""),
        }
