"""Head process entry: runs the HeadServer until killed.

Analog of the reference's `gcs_server` binary entry
(reference: src/ray/gcs/gcs_server/gcs_server_main.cc) — spawned by
ray_tpu.init() on the driver node or by `ray-tpu start --head`.
Prints ``PORT <n>`` on stdout once listening so the parent can connect.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys


async def _amain(args):
    from ray_tpu._private.config import RayConfig

    if args.system_config:
        RayConfig.initialize_from_json(args.system_config)
    from ray_tpu.gcs.server import HeadServer

    server = HeadServer(
        host=args.host,
        port=args.port,
        resources=json.loads(args.resources) if args.resources else None,
        session_dir=args.session_dir,
        store_capacity=args.object_store_memory,
    )
    port = await server.start()
    print(f"PORT {port}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="")
    parser.add_argument("--session-dir", default="/tmp/ray_tpu")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--system-config", default=os.environ.get("RAY_TPU_SYSTEM_CONFIG", ""))
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args()
    logging.basicConfig(
        level=args.log_level,
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s",
        stream=sys.stderr,
    )
    profile_path = os.environ.get("RAY_TPU_HEAD_PROFILE", "")
    if profile_path:
        # dev/perf diagnosis: profile the head's event loop, dump on exit
        import cProfile

        pr = cProfile.Profile()
        pr.enable()
        try:
            asyncio.run(_amain(args))
        finally:
            pr.disable()
            pr.dump_stats(profile_path)
    else:
        asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
