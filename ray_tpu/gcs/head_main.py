"""Head process entry: runs the HeadServer until killed.

Analog of the reference's `gcs_server` binary entry
(reference: src/ray/gcs/gcs_server/gcs_server_main.cc) — spawned by
ray_tpu.init() on the driver node or by `ray-tpu start --head`.
Prints ``PORT <n>`` on stdout once listening so the parent can connect.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys


async def _amain(args):
    from ray_tpu._private.config import RayConfig

    if args.system_config:
        RayConfig.initialize_from_json(args.system_config)
    from ray_tpu.gcs.server import HeadServer

    server = HeadServer(
        host=args.host,
        port=args.port,
        resources=json.loads(args.resources) if args.resources else None,
        session_dir=args.session_dir,
        store_capacity=args.object_store_memory,
    )
    port = await server.start()
    print(f"PORT {port}", flush=True)
    # join the structured log plane: stderr (→ head.log) is wrapped,
    # stdout stays raw — it is the PORT handshake pipe the parent reads,
    # not a log.  basicConfig's StreamHandler captured the REAL stderr at
    # startup; re-point it at the wrapper so logging output is stamped
    # once instead of landing raw beside a structured duplicate.
    from ray_tpu._private import log_plane

    if log_plane.install(node="head", wrap_stdout=False, logging_handler=False):
        for h in logging.getLogger().handlers:
            if isinstance(h, logging.StreamHandler) and h.stream is sys.stderr.raw:
                h.stream = sys.stderr

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()


def main():
    # on-demand stack dumps, same registration as every worker:
    # `kill -USR1 <head pid>` writes all thread tracebacks to head.log
    from ray_tpu._private.profiler import install_sigusr1

    install_sigusr1()
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="")
    parser.add_argument("--session-dir", default="/tmp/ray_tpu")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--system-config", default=os.environ.get("RAY_TPU_SYSTEM_CONFIG", ""))
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args()
    logging.basicConfig(
        level=args.log_level,
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s",
        stream=sys.stderr,
    )
    profile_path = os.environ.get("RAY_TPU_HEAD_PROFILE", "")
    if profile_path:
        # DEPRECATED alias for the old cProfile hack: now routes through
        # the cluster sampling profiler (_private/profiler.py) — arms
        # head-role sampling at startup and writes the head's folded
        # stacks (flamegraph collapsed format, not pstats) to the path on
        # exit.  Prefer `ray-tpu profile` / RAY_TPU_PROFILER=1.
        print(
            "RAY_TPU_HEAD_PROFILE is deprecated: arming the sampling "
            "profiler for the head role; output is collapsed-stack text "
            f"at {profile_path} (use `ray-tpu profile` instead)",
            file=sys.stderr,
            flush=True,
        )
        # arm THIS process directly — never via os.environ, which every
        # head-spawned worker inherits (dict(os.environ) in the spawn
        # path): the alias promises head-role profiling, not a silently
        # armed sampler in every worker on the node.  An explicit
        # RAY_TPU_PROFILER=0 (plane excised) still wins inside arm().
        from ray_tpu._private import profiler

        profiler.maybe_init_from_env("head")
        profiler.arm()
    try:
        asyncio.run(_amain(args))
    finally:
        if profile_path:
            from ray_tpu._private import profiler

            # lifetime view: a mid-run cluster-wide disarm (any
            # `ray-tpu profile snapshot`) retires the sampler but must
            # not empty the exit dump the operator asked for
            stacks = profiler.local_totals(lifetime=True)
            if stacks:
                with open(profile_path, "w") as f:
                    f.write(profiler.folded_text(stacks))


if __name__ == "__main__":
    main()
