"""Head server: cluster metadata authority + scheduler + object directory.

TPU-native analog of the reference's GCS server + raylet control logic
(reference: src/ray/gcs/gcs_server/gcs_server.cc — NodeInfo/ActorInfo/
PlacementGroupInfo/JobInfo/KV/Pubsub services; src/ray/raylet/
node_manager.cc + scheduling/cluster_task_manager.cc for leasing and
dispatch).  One asyncio process serves:

- node registry + worker pool directives (spawn/kill) per node
- cluster task scheduling (hybrid pack/spread policy, resource accounting)
- actor directory + FSM (pending → alive → restarting/dead), named actors
- placement groups (PACK/SPREAD/STRICT_PACK/STRICT_SPREAD) with resource
  reservation and bundle accounting
- object directory (pending → sealed/error) with waiter wakeup
- cluster-wide KV (function table, collective rendezvous), pubsub channels

Design deltas from the reference, deliberate for the TPU era:
- Control is a star over length-prefixed msgpack/TCP instead of per-pair
  gRPC meshes; the data plane (tensors) never touches it — large values live
  in the node-local shared-memory store (src/object_store/store.cc) and move
  across chips over ICI via jax collectives, not through this server.
- Scheduling decisions are centralized here rather than spilled-back raylet
  to raylet (reference cluster_task_manager.cc:80): with slice-aligned TPU
  topology the global view is what placement quality needs.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import chaos
from ray_tpu._private import log_plane as _log_plane
from ray_tpu._private import profiler as _profiler
from ray_tpu._private import task_events as _task_events
from ray_tpu._private.config import RayConfig
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.protocol import Connection, MsgType
from ray_tpu._private.task_spec import ACTOR_CREATION_TASK, ACTOR_TASK, NORMAL_TASK, TaskSpec

logger = logging.getLogger("ray_tpu.gcs")

# Object table states (analog: reference object directory + task states)
PENDING, SEALED, ERRORED = 0, 1, 2


def _percentiles(vals: List[float]) -> dict:
    """Nearest-rank percentile row shared by every summary surface."""
    vals = sorted(vals)
    n = len(vals)
    if n == 0:
        return {"count": 0, "p50": 0.0, "p95": 0.0, "p99": 0.0, "max": 0.0, "mean": 0.0}
    return {
        "count": n,
        "p50": vals[int(0.50 * (n - 1))],
        "p95": vals[int(0.95 * (n - 1))],
        "p99": vals[int(0.99 * (n - 1))],
        "max": vals[-1],
        "mean": sum(vals) / n,
    }

# Actor FSM states (reference: gcs_actor_manager.cc state machine).
# PREEMPTED is the one addition over the reference: the scheduler evicted
# the actor by policy (checkpoint saved, resources released) and it parks
# until capacity returns — distinct from RESTARTING so the worker-death
# path knows not to charge the fault-restart budget.
ACTOR_PENDING, ACTOR_ALIVE, ACTOR_RESTARTING, ACTOR_DEAD, ACTOR_PREEMPTED = (
    "PENDING_CREATION",
    "ALIVE",
    "RESTARTING",
    "DEAD",
    "PREEMPTED",
)


class WorkerInfo:
    __slots__ = (
        "worker_id",
        "node_id",
        "conn",
        "pid",
        "idle",
        "actor_id",
        "running_tasks",
        "started_at",
        "idle_since",
        "dedicated",
        "has_tpu",
        "direct_addr",
        "lease",
        "log_file",
    )

    def __init__(
        self, worker_id: bytes, node_id: bytes, conn: Connection, pid: int, has_tpu: bool = False
    ):
        self.worker_id = worker_id
        self.node_id = node_id
        self.conn = conn
        self.pid = pid
        self.idle = True
        self.actor_id: Optional[bytes] = None
        self.running_tasks: Set[bytes] = set()
        self.started_at = time.time()
        self.idle_since = time.time()
        self.dedicated = False  # actor-dedicated workers never return to pool
        self.has_tpu = has_tpu  # spawned with the TPU claim env intact
        # dialable host:port of the worker's direct-call server (every
        # worker runs one now — the lease fast path pushes tasks here)
        self.direct_addr = ""
        # active worker lease (control-plane fast path): {"lease_id",
        # "cid", "resources", "priority", "via", "granted_at", "revoking"}
        self.lease: Optional[dict] = None
        # absolute path of the worker's log file on ITS node (from
        # registration) — LOG_FETCH entity resolution starts here
        self.log_file = ""


class NodeInfo:
    """Node bookkeeping.  Resource accounting is delegated to the native
    scheduling core (src/scheduler/scheduler.cc via core/native_scheduler.py
    — fixed-point math, hybrid policy), the analog of the reference's C++
    ClusterResourceManager (src/ray/raylet/scheduling/)."""

    __slots__ = (
        "node_id",
        "conn",
        "resources_total",
        "store_path",
        "alive",
        "workers",
        "starting_workers",
        "labels",
        "address",
        "transfer_addr",
        "store_stats",
        "idle_pool",
        "_sched",
    )

    def __init__(
        self,
        node_id: bytes,
        conn: Optional[Connection],
        resources: Dict[str, float],
        store_path: str,
        sched=None,
    ):
        self.node_id = node_id
        self.conn = conn  # raylet connection (None for the head's own node)
        self.resources_total = dict(resources)
        self.store_path = store_path
        self.alive = True
        self.workers: Dict[bytes, WorkerInfo] = {}
        # O(1) idle-worker index, split by TPU claim: _find_idle_worker /
        # the scheduler's capacity count were O(total workers) per call,
        # which is what made 600-actor fleets quadratic at the head
        self.idle_pool: Dict[bool, Set[bytes]] = {False: set(), True: set()}
        self.starting_workers = 0
        self.labels: Dict[str, str] = {}
        self.address = ""
        self.transfer_addr = ""
        # freshest shm-store occupancy reported on this node's heartbeat
        # (the head's own node is sampled directly by the observer loop)
        self.store_stats: Dict[str, float] = {}
        self._sched = sched
        if sched is not None:
            sched.upsert_node(node_id, self.resources_total)

    @property
    def resources_available(self) -> Dict[str, float]:
        avail = self._sched.available(self.node_id)
        # only report resource types this node actually has
        return {k: avail.get(k, 0.0) for k in self.resources_total}

    def can_fit(self, demand: Dict[str, float]) -> bool:
        avail = self._sched.available(self.node_id)
        for k, v in demand.items():
            if v > 0 and avail.get(k, 0.0) + 1e-9 < v:
                return False
        return True

    def total_fit(self, demand: Dict[str, float]) -> bool:
        for k, v in demand.items():
            if v > 0 and self.resources_total.get(k, 0.0) + 1e-9 < v:
                return False
        return True

    def acquire(self, demand: Dict[str, float]):
        self._sched.acquire(self.node_id, demand, force=True)

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        return self._sched.acquire(self.node_id, demand, force=False)

    def release(self, demand: Dict[str, float]):
        self._sched.release(self.node_id, demand)

    def utilization(self) -> float:
        return self._sched.utilization(self.node_id)

    # ---- idle-worker index (kept in lockstep with WorkerInfo.idle) ----

    def mark_idle(self, w: "WorkerInfo"):
        w.idle = True
        w.idle_since = time.time()
        if not w.dedicated and w.actor_id is None and w.lease is None:
            self.idle_pool[w.has_tpu].add(w.worker_id)

    def mark_busy(self, w: "WorkerInfo"):
        w.idle = False
        self.idle_pool[w.has_tpu].discard(w.worker_id)

    def forget_worker(self, w: "WorkerInfo"):
        self.workers.pop(w.worker_id, None)
        self.idle_pool[w.has_tpu].discard(w.worker_id)

    def pop_idle(self, needs_tpu: bool) -> Optional["WorkerInfo"]:
        pool = self.idle_pool[needs_tpu]
        while pool:
            wid = next(iter(pool))
            pool.discard(wid)
            w = self.workers.get(wid)
            if w is not None and w.idle and w.actor_id is None and not w.dedicated and w.lease is None:
                w.idle = False
                return w
        return None


class ActorInfo:
    __slots__ = (
        "actor_id",
        "state",
        "worker_id",
        "node_id",
        "creation_spec",
        "name",
        "namespace",
        "detached",
        "max_restarts",
        "restarts_used",
        "pending_calls",
        "death_cause",
        "death_log_tail",
        "owner_conn_id",
        "direct_addr",
        "creation_cpu_released",
    )

    def __init__(self, spec: TaskSpec):
        self.actor_id = spec.actor_id
        self.state = ACTOR_PENDING
        self.creation_cpu_released = False
        self.worker_id: Optional[bytes] = None
        self.node_id: Optional[bytes] = None
        self.creation_spec = spec
        self.name = spec.name
        self.namespace = spec.namespace
        self.detached = spec.detached
        self.max_restarts = spec.max_restarts
        self.restarts_used = 0
        self.pending_calls: List[TaskSpec] = []
        self.death_cause = ""
        # LOG_TAIL_MARKER suffix captured at death from the victim
        # worker's recent-line ring; appended to every seal string so
        # late calls to the dead actor still surface the forensics
        self.death_log_tail = ""
        self.owner_conn_id: Optional[int] = None
        # "host:port" of the worker's direct-call server (reference analog:
        # the worker address a DirectActorSubmitter pushes to,
        # direct_actor_task_submitter.cc)
        self.direct_addr: str = ""


class PlacementGroupInfo:
    __slots__ = ("pg_id", "bundles", "strategy", "name", "state", "bundle_nodes", "waiters", "bundle_available")

    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]], strategy: str, name: str):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"
        self.bundle_nodes: List[Optional[bytes]] = [None] * len(bundles)
        # per-bundle remaining resources (consumed by tasks placed in it)
        self.bundle_available: List[Dict[str, float]] = [dict(b) for b in bundles]
        self.waiters: List[asyncio.Future] = []


class TaskEntry:
    """A task known to the scheduler: queued, leased, or running."""

    __slots__ = (
        "spec", "state", "worker_id", "node_id", "caller_conn_id", "blocked",
        "wire", "res_shape", "enqueued_at", "preempted", "preempt_count",
        "preempt_requested_at",
    )

    def __init__(self, spec: TaskSpec, caller_conn_id: int, wire=None):
        self.spec = spec
        self.state = "QUEUED"
        self.worker_id: Optional[bytes] = None
        self.node_id: Optional[bytes] = None
        self.caller_conn_id = caller_conn_id
        self.blocked = False  # worker released cpu while waiting in get()
        self.res_shape = None  # cached sorted resource tuple (scheduler scan)
        # queue-wait clock for fair-share deficits + starvation boosts;
        # independent of the flight recorder so priorities work with
        # RAY_TPU_TASK_EVENTS=0 (it measures the same head_enqueue→dispatch
        # window the queue_wait phase records)
        self.enqueued_at = time.time()
        # preemption accounting: the scheduler killed this running task by
        # policy (requeue, don't charge the fault-retry budget); the count
        # seals a typed PreemptedError once the preemption budget is spent.
        # Seeded from the spec so preemptions a task already suffered on a
        # revoked lease (driver-side resubmit) stay on the same budget.
        self.preempted = False
        self.preempt_count = int(getattr(spec, "preempt_count", 0) or 0)
        self.preempt_requested_at = 0.0  # rate-limits victim scans per entry
        # the submit frame's wire form, reused verbatim for the PUSH_TASK
        # dispatch — re-encoding the spec per hop was measurable on the
        # task hot path
        self.wire = wire


class HeadServer:
    """The cluster brain.  One instance per cluster."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        resources: Optional[Dict[str, float]] = None,
        store_path: str = "",
        store_capacity: int = 0,
        session_dir: str = "",
    ):
        self.host = host
        self.port = port
        self.session_dir = session_dir or "/tmp/ray_tpu"
        self.store_path = store_path or os.path.join(self.session_dir, "store")
        self.store_capacity = store_capacity or RayConfig.object_store_memory
        self._server: Optional[asyncio.AbstractServer] = None

        from ray_tpu.core.native_scheduler import NativeScheduler

        self.sched = NativeScheduler()
        self.nodes: Dict[bytes, NodeInfo] = {}
        self.head_node_id = NodeID.from_random().binary()
        self._head_resources = resources or {}

        self.workers: Dict[bytes, WorkerInfo] = {}
        self.actors: Dict[bytes, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}
        self.pgs: Dict[bytes, PlacementGroupInfo] = {}
        self.jobs: Dict[bytes, dict] = {}

        # object directory: oid -> [state, error_payload]
        self.objects: Dict[bytes, List] = {}
        self.object_waiters: Dict[bytes, List[asyncio.Future]] = {}
        self.object_refcounts: Dict[bytes, int] = {}
        # container oid -> ids of refs pickled inside its value.  While the
        # container is in scope its inner objects are pinned (one refcount
        # each), closing the sender-releases-before-receiver-registers race
        # (analog: reference reference_count.cc borrower/containment protocol)
        self.object_contained: Dict[bytes, List[bytes]] = {}
        # oid -> set of node_ids holding a sealed copy (analog: reference
        # OwnershipBasedObjectDirectory location sets)
        self.object_locations: Dict[bytes, set] = {}
        # oid -> (node_id, path): objects whose only durable copy is a
        # spill file on that node's disk (reference analog: spilled-URL
        # tracking, raylet/local_object_manager.h)
        self.object_spilled: Dict[bytes, tuple] = {}
        # (oid, dest_node) -> future, coalescing concurrent pull requests
        self._pull_inflight: Dict[Tuple[bytes, bytes], asyncio.Future] = {}
        # lineage: return oid -> producing TaskSpec, byte-budgeted FIFO
        # (analog: reference TaskManager lineage pinning, task_manager.h:91-105
        # + ObjectRecoveryManager, object_recovery_manager.h:90)
        self.lineage: Dict[bytes, TaskSpec] = {}
        self._lineage_bytes: Dict[bytes, int] = {}
        self._lineage_total = 0
        self._reconstructions: Dict[bytes, int] = {}

        # cluster KV: a lock-partitioned thread-safe store shared with the
        # GCS shard servers (gcs/shards.py) — the head's internal reads and
        # writes and the shard listeners operate on the SAME table, so
        # sharding is purely a question of which event loop serves an RPC
        from ray_tpu.gcs.shards import ActorMirror, GcsShardServer, ObjectMirror, ShardedKV

        self.kv = ShardedKV(max(1, RayConfig.gcs_kv_shards or 1))
        # read replicas of the object seal-state + actor directory, written
        # through on every head-side transition and served by the shards
        self._obj_mirror = ObjectMirror()
        self._actor_mirror = ActorMirror()
        self._shard_server: Optional[GcsShardServer] = None
        self.shard_addrs: List[str] = []
        # pubsub: channel -> {conn_id: Connection}
        self.subscribers: Dict[str, Dict[int, Connection]] = {}

        self.task_queue: List[TaskEntry] = []
        self.tasks: Dict[bytes, TaskEntry] = {}  # leased/running by task id
        self.finished_task_count = 0
        # worker-lease fast path: lease_id -> worker_id, plus the holder
        # index (head-granted leases die with their driver connection)
        self.leases: Dict[bytes, bytes] = {}
        self._leases_by_conn: Dict[int, Set[bytes]] = {}
        # rolling task-execution event log for `ray-tpu timeline` (analog:
        # reference core_worker/profiling.cc → GCS → chrome trace)
        from collections import deque

        self.timeline: "deque" = deque(maxlen=10000)
        # structured cluster events (analog: reference src/ray/util/event.h
        # + dashboard event module): lifecycle transitions worth surfacing
        # to operators, ring-buffered and queryable via LIST_EVENTS
        self.events: "deque" = deque(maxlen=5000)
        # flight recorder (task_events.py): per-task joined phase records —
        # the source for TASK_SUMMARY / `ray-tpu summary tasks`; per-phase
        # histograms live in self.kv under metrics:* (written via
        # _observe_phase) so every metrics scrape surface sees them
        self.task_records: "deque" = deque(maxlen=4096)
        # parsed histogram records cached by kv key: one json.dumps per
        # observe instead of a loads+dumps round trip on the done path
        self._phase_hist_cache: Dict[str, dict] = {}
        # workload-plane observability (serve/train/memory + SLO watchdog)
        # object accounting sidecar: oid -> {"nbytes", "owner"} stamped at
        # seal time (owner derived from the sealing connection)
        self.object_meta: Dict[bytes, dict] = {}
        # device-resident object tier (core/DEVICE_TIER.md): oid ->
        # {"meta": {kind,dtype,shape,nbytes}, "holders": {addr: {"token",
        # "cid", "conn", "node_id", "pulls": [time.time(), ...]}}}.
        # Deliberately NOT WAL-persisted: device buffers die with their
        # processes across a head restart, so a recovered head resolves
        # these objects via shm envelopes or lineage instead.
        self.device_objects: Dict[bytes, dict] = {}
        # consumers parked because every live holder is at its
        # device_pull_fanout cap; woken when a pull slot frees (pulled_from
        # re-registration) or a new holder joins the fan-out tree
        self._device_slot_waiters: Dict[bytes, List[asyncio.Future]] = {}
        # freshest rolling stats per train run (TRAIN_STEP frames)
        self.train_stats: Dict[str, dict] = {}
        # freshest DAG channel ring occupancy samples (DAG_STEP frames)
        self.dag_channel_stats: Dict[str, dict] = {}
        # SLO watchdog: spec blob cache + one evaluator and verdict per slo
        self._slo_specs_blob: Optional[bytes] = None
        self._slo_specs: List[dict] = []
        self._slo_evals: Dict[str, object] = {}
        self._slo_state: Dict[str, dict] = {}
        # multi-tenant preemption (ROADMAP item 5): within-band fair-share
        # deficits keyed by (band, job), accumulated from queue-wait and
        # drained per dispatch
        self._job_deficit: Dict[Tuple[int, bytes], float] = {}
        self._fair_tick_at = time.time()
        # actors evicted by policy (checkpoint saved, resources released),
        # parked until capacity returns: actor_id -> parked-since ts
        self._preempted_parked: Dict[bytes, float] = {}
        # actors with a PREEMPT_ACTOR rpc in flight (double-preempt guard)
        self._preempting: Set[bytes] = set()
        # rolling preemption log → `ray-tpu summary preemptions`
        self._preempt_log: "deque" = deque(maxlen=512)
        # head-owned ray_tpu_preemptions_total{band,kind} counter records
        self._counter_cache: Dict[str, dict] = {}
        # SLO policy: while a preempt_below_band SLO burns, new low-band
        # re-admissions hold; recovery clears it and parked work returns
        self._slo_preempt_hold = False
        self._slo_breach_ticks: Dict[str, int] = {}
        self._last_policy_preempt = 0.0
        self._preempt_scans_left = 0  # per-tick victim-scan budget
        # SLO scale policy (serve/FLEET.md): per-spec breach/recovery tick
        # counters, outstanding scale-out debt (bounds scale-in so
        # recovery never drains below what the policy added), and a
        # per-deployment cooldown stamp
        self._slo_scale_ticks: Dict[str, int] = {}
        self._slo_recover_ticks: Dict[str, int] = {}
        self._slo_scale_debt: Dict[str, int] = {}
        self._last_policy_scale: Dict[str, float] = {}
        # cluster-wide sampling profiler (_private/profiler.py): folded
        # stacks aggregated per (role, node) from batched PROFILE_STATS
        # frames, flush-window slices for the chrome timeline, one-shot
        # native stack dumps (`ray-tpu stacks`), and the active control
        # record (mirrors kv "profile:ctrl" for status without a parse)
        self.profile_stacks: Dict[Tuple[str, str], Dict[str, int]] = {}
        self.profile_meta: Dict[Tuple[str, str], dict] = {}
        self.profile_slices: "deque" = deque(maxlen=2048)
        self.profile_stack_dumps: List[dict] = []
        self.profile_ctrl: Optional[dict] = None

        # ---- structured log plane (util/OBSERVABILITY.md "Logs") ----
        # error ring + signature-dedup index behind `summary errors`
        # (the resurrected ERROR_PUSH role, MsgType.ERROR_REPORT)
        self.error_records: "deque" = deque(maxlen=512)
        self._error_index: Dict[str, dict] = {}
        # driver conn -> job id, for job-scoped "logs" fan-out (two
        # concurrent drivers each see only their own workers' lines)
        self._conn_job: Dict[int, bytes] = {}
        # per-source recent-line ring fed by the logs pubsub transit:
        # the forensics tail attached to ActorDiedError when the victim
        # process died without shipping its own (source = log basename)
        self._recent_logs: Dict[str, "deque"] = {}
        # worker id -> {"node", "path", "src"}, kept past worker death
        # (the ring above outlives the WorkerInfo; this is how a dead
        # actor's seal finds its victim's tail, and how LOG_FETCH still
        # resolves an exited worker's file)
        self._worker_log_src: Dict[bytes, dict] = {}
        # log records carrying trace ids, rendered into ray_tpu.timeline()
        # as instant markers ("which line printed during which phase")
        self._log_trace_marks: "deque" = deque(maxlen=2048)

        # ---- head fault tolerance (gcs/HEAD_FT.md) ----
        # per-boot incarnation: 1 on a fresh session, +1 per restart in
        # the same session dir (persisted in head_meta.json + WAL'd)
        self.incarnation = 1
        self.started_at = time.time()
        # active recovery grace window (None when not recovering): holds
        # dispatch while live peers re-attach; state not reconfirmed by
        # the deadline is reaped through the existing fault machinery
        self._recovery: Optional[dict] = None
        self.last_recovery: Optional[dict] = None
        # resubmits / actor calls / lease restores parked until the grace
        # window closes (reconciliation decides dedupe vs enqueue)
        self._recovery_resubmits: List[Tuple[int, dict]] = []
        self._recovery_actor_calls: List[TaskSpec] = []
        # holder-announced leases whose worker hasn't reattached yet,
        # keyed by worker id and drained when that worker announces — a
        # standing structure (NOT recovery-scoped) because a worker's
        # redial can outlast the grace window
        self._pending_lease_restores: Dict[bytes, List[Tuple[int, dict]]] = {}
        # driver-announced actor ownership claims: applied immediately to
        # known actors, and retained so a WORKER announce that lands after
        # its owner's reattach still binds to the right conn
        self._owner_claims: Dict[bytes, int] = {}
        self._reattach_stats = {
            "nodes": 0,
            "workers": 0,
            "drivers": 0,
            "actors": 0,
            "tasks": 0,
            "leases": 0,
        }
        # TASK_DONE replay dedupe: a reattached worker re-sends its recent
        # completions (the head may or may not have processed them before
        # the crash / conn loss) — processing one twice would double-pin
        # contained refs and double-count metrics
        self._recent_dones: Set[bytes] = set()
        from collections import deque as _deque

        self._recent_dones_fifo: "_deque" = _deque(maxlen=8192)
        # ref-batch dedupe: clients tag ADD_REF/REMOVE_REF flushes with a
        # batch id and re-send after a conn loss (the loss may have raced
        # the reply) — a counter bump is not idempotent, so dedupe here
        self._ref_batches: Set[bytes] = set()
        self._ref_batches_fifo: "_deque" = _deque(maxlen=4096)
        # True on a restarted head: pre-crash client refcounts were never
        # re-announced, so an ABSENT count is "unknown", not zero
        self._refs_amnesic = False
        self._store_preserved = False

        self._conn_seq = 0
        self._last_beat: Dict[int, float] = {}
        self._conns: Dict[int, Connection] = {}
        self._conn_kind: Dict[int, str] = {}  # driver|worker|raylet
        self._conn_worker: Dict[int, bytes] = {}
        self._conn_node: Dict[int, bytes] = {}
        self._sched_wakeup = asyncio.Event()
        self._shutdown = False
        self._storage = None
        self._tables_dirty = False
        self._worker_env: Dict[str, str] = {}
        self._next_worker_seq = 0
        self._zygote = None  # warm fork server for pool workers

    # ------------------------------------------------------------------ setup

    def _load_head_meta(self) -> Optional[dict]:
        """One-shot boot IO (before any client is served): the previous
        incarnation's identity record, or None on a fresh session."""
        import json as _json

        try:
            with open(self._head_meta_path) as f:
                return _json.load(f)
        except (OSError, ValueError):
            return None

    def _save_head_meta(self):
        """Persist identity for the NEXT incarnation (atomic replace);
        one-shot boot IO, runs before any client traffic is accepted."""
        import json as _json

        try:
            tmp = self._head_meta_path + ".tmp"
            with open(tmp, "w") as f:
                _json.dump(
                    {
                        "node_id": self.head_node_id.hex(),
                        "port": self.port,
                        "incarnation": self.incarnation,
                        "pid": os.getpid(),
                    },
                    f,
                )
            os.replace(tmp, self._head_meta_path)
        except OSError:
            logger.warning("head_meta.json write failed; restarts lose identity", exc_info=True)

    async def start(self) -> int:
        os.makedirs(self.session_dir, exist_ok=True)
        # head identity persistence: a restarted head in the SAME session
        # dir adopts its predecessor's node id (so surviving workers'
        # RAY_TPU_NODE_ID and the replayed object directory stay valid),
        # its listen port when none was pinned (so peers' redial loops
        # find it), and the next incarnation number
        self._head_meta_path = os.path.join(self.session_dir, "head_meta.json")
        prev_meta = self._load_head_meta()
        if prev_meta:
            try:
                self.head_node_id = bytes.fromhex(prev_meta["node_id"])
                self.incarnation = int(prev_meta.get("incarnation", 1)) + 1
            except (KeyError, ValueError):
                prev_meta = None
        # chaos scope + env-armed plan; fired faults land in the cluster
        # event ring directly (this process OWNS the ring)
        chaos.maybe_init_from_env("head")
        chaos.set_emitter(self._chaos_emit)
        # profiler scope + emitter: the head ingests its own folded-stack
        # frames directly, marshalled onto this loop — the sampler thread
        # must never touch the tables the loop owns (RAY_TPU_PROFILER=1
        # in the env arms head-role sampling from startup; the deprecated
        # RAY_TPU_HEAD_PROFILE alias in head_main routes here too)
        _profiler.maybe_init_from_env("head")
        if _profiler.aware():
            _head_loop = asyncio.get_running_loop()

            def _profile_emit(payload: dict, _loop=_head_loop):
                try:
                    _loop.call_soon_threadsafe(
                        self._ingest_profile_frame,
                        dict(payload, node_id=self.head_node_id),
                    )
                except RuntimeError:
                    pass  # loop already closed (shutdown): frame dropped

            _profiler.set_emitter(_profile_emit)
        # head's own node
        res = dict(self._head_resources)
        res.setdefault("CPU", float(os.cpu_count() or 4))
        res.setdefault("memory", 4.0 * (1 << 30))
        res.setdefault("object_store_memory", float(self.store_capacity))
        node = NodeInfo(self.head_node_id, None, res, self.store_path, sched=self.sched)
        node.labels["node_type"] = "head"
        self.nodes[self.head_node_id] = node
        # create the shm store segment for the head node
        from ray_tpu.core.shm_store import ShmObjectStore
        from ray_tpu.raylet.object_agent import ObjectTransferAgent

        # a restarted head ATTACHES to the surviving store segment instead
        # of recreating it: objects produced before the crash stay
        # readable, and surviving workers' mmaps of the same file remain
        # coherent (recreating would silently split-brain them)
        self._store_preserved = False
        if prev_meta and os.path.exists(self.store_path):
            try:
                self._store = ShmObjectStore(self.store_path, create=False)
                self._store_preserved = True
            except OSError:
                logger.warning(
                    "surviving store segment unusable; recreating (its "
                    "objects are lost — lineage/spill recovery applies)"
                )
        if not self._store_preserved:
            self._store = ShmObjectStore(
                self.store_path, capacity=self.store_capacity, create=True
            )
        if RayConfig.object_spilling_enabled:
            loop = asyncio.get_running_loop()
            spill_dir = self.store_path + ".spill"

            def _head_spill_hook(need: int) -> bool:
                # fires on whatever thread hit pressure (restore runs in an
                # executor; the agent's pulls run on the loop); registry
                # updates are marshalled back onto the loop
                from ray_tpu.raylet.spill import spill_batch

                spilled = spill_batch(self._store, int(need), spill_dir)
                if not spilled:
                    return False
                loop.call_soon_threadsafe(
                    self._record_spills, self.head_node_id, spilled
                )
                return True

            self._store.spill_hook = _head_spill_hook
        # the head node participates in the transfer mesh like any raylet;
        # advertise a dialable address (bind wildcard → route-based self-IP)
        self.object_agent = ObjectTransferAgent(self._store)
        transfer_port = await self.object_agent.start()
        if self.host not in ("0.0.0.0", ""):
            advertise = self.host
        else:
            from ray_tpu.util.collective.dcn_backend import _self_ip

            advertise = os.environ.get("RAY_TPU_NODE_IP") or _self_ip()
        node.transfer_addr = f"{advertise}:{transfer_port}"

        # GCS shards: per-shard event loops + listeners for the KV /
        # object-locate / actor-directory read planes, so those RPCs stop
        # serializing behind task dispatch on this loop.  Shard-side table
        # mutations marshal their WAL records back here (the WAL fd is
        # owned by the head loop's persist machinery).
        nshards = RayConfig.gcs_kv_shards
        if nshards > 0:
            from ray_tpu.gcs.shards import GcsShardServer

            head_loop = asyncio.get_running_loop()

            def _shard_wal(*record):
                head_loop.call_soon_threadsafe(self._wal, *record)

            self._shard_server = GcsShardServer(
                self.kv,
                self._obj_mirror,
                self._actor_mirror,
                host=self.host,
                wal_cb=_shard_wal,
                dirty_cb=self._mark_tables_dirty,
            )
            self.shard_addrs = self._shard_server.start(nshards, advertise=advertise)

        # head node's own Prometheus scrape endpoint (raylets run their own)
        from ray_tpu.raylet.metrics_agent import start_metrics_server

        def _head_app_metrics() -> str:
            # the agent shares this process and loop: render the app
            # metrics (incl. flight-recorder phase histograms) straight
            # from the kv table, no connected worker needed
            from ray_tpu.util import metrics as metrics_mod

            return metrics_mod.render_prometheus(
                metrics_mod.merge_series(metrics_mod.raw_records_from_kv(self.kv))
            )

        try:
            mport = await start_metrics_server(
                self.head_node_id.hex(), self._store, app_metrics=_head_app_metrics
            )
            node.labels["metrics_addr"] = f"{advertise}:{mport}"
        except Exception as e:  # noqa: BLE001
            logger.warning("head metrics endpoint unavailable: %s", e)

        if self.port == 0 and prev_meta and prev_meta.get("port"):
            # reclaim the predecessor's port so peers' redial loops reach
            # us without rediscovery; fall back to an ephemeral port if
            # something else grabbed it (peers then fail their window —
            # same as a head that never came back)
            try:
                self._server = await asyncio.start_server(
                    self._on_connection, self.host, int(prev_meta["port"])
                )
            except OSError:
                logger.warning(
                    "predecessor port %s unavailable; binding ephemeral",
                    prev_meta["port"],
                )
                self._server = await asyncio.start_server(
                    self._on_connection, self.host, 0
                )
        else:
            self._server = await asyncio.start_server(self._on_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._save_head_meta()

        # tail this node's worker logs → "logs" pubsub channel (analog:
        # reference log_monitor.py; drivers subscribe when log_to_driver)
        from ray_tpu._private.log_monitor import LogTailer

        loop = asyncio.get_running_loop()

        def _publish_logs(msg: dict):
            asyncio.run_coroutine_threadsafe(self._publish("logs", msg), loop)

        # head-spawned workers only — raylets tail their own node's files.
        # driver-*.log rides along: the driver tee (log_plane) lands its
        # structured records there, making driver output job-addressable
        self._log_tailer = LogTailer(
            self.session_dir,
            _publish_logs,
            pattern="worker-head-*.log|driver-*.log",
            rotation_bytes=RayConfig.log_rotation_bytes,
            rotation_backups=RayConfig.log_rotation_backups,
        )
        self._log_tailer.start()
        # zero-init the log plane's metric families so scrapes see them
        # before the first line / error flows (prom_validate contract)
        self._inc_counter(
            "ray_tpu_log_lines_total",
            "log lines transiting the head's logs channel, by stream/node",
            {"stream": "out", "node": "head"},
            0.0,
        )
        self._inc_counter(
            "ray_tpu_log_lines_total",
            "log lines transiting the head's logs channel, by stream/node",
            {"stream": "err", "node": "head"},
            0.0,
        )
        for kind in ("task", "actor_task", "actor_death"):
            self._inc_counter(
                "ray_tpu_error_records_total",
                "structured error records in the head's dedup ring, by kind",
                {"kind": kind},
                0.0,
            )
        # table persistence: restore surviving metadata from a prior head
        # incarnation (detached actors restart on fresh workers; spilled /
        # lineage-backed objects stay recoverable), then append every
        # mutation to the WAL and compact when it grows (analog: reference
        # gcs_table_storage.h → redis_store_client.h per-write persistence)
        from ray_tpu.gcs.storage import GcsWalStorage

        self._storage = GcsWalStorage(self.session_dir)
        self._compact_lock = asyncio.Lock()
        # recovery grace window: a RESTARTED head holds dispatch while
        # live peers redial and re-announce; set BEFORE restore so the
        # replayed detached-actor creations park for reclaim instead of
        # immediately respawning actors whose workers may still be alive
        if self.incarnation > 1:
            # pre-crash client refs were never re-announced: an absent
            # refcount must err toward retention, not deletion
            self._refs_amnesic = True
        if self.incarnation > 1 and RayConfig.head_recovery_grace_s > 0:
            self._recovery = {
                "started": time.time(),
                "deadline": time.time() + RayConfig.head_recovery_grace_s,
                "unclaimed_actors": set(),
            }
            # stats cover THIS window only (a second restart must not
            # re-report the first recovery's reattaches)
            self._reattach_stats = {k: 0 for k in self._reattach_stats}
        self._restore_tables()
        # identity record: lets the NEXT incarnation remap directory/spill
        # entries that point at THIS head's (ephemeral) store segment
        self._wal("head", self.head_node_id)
        self._wal("boot", self.incarnation, time.time())
        if self.incarnation > 1:
            self._record_event(
                "WARNING",
                "head",
                f"head restarted (incarnation {self.incarnation}, store "
                f"{'preserved' if self._store_preserved else 'recreated'})",
                incarnation=self.incarnation,
            )
            self._inc_counter(
                "ray_tpu_head_restarts_total",
                "head process restarts within this session",
                {},
                1.0,
            )
            if self._recovery is not None:
                asyncio.get_running_loop().create_task(self._recovery_window())

        # SLO specs can be seeded from the environment (operators without a
        # driver attached yet); a later slo_api.set_slos replaces them
        env_specs = os.environ.get("RAY_TPU_SLO_SPECS", "").strip()
        if env_specs and "slo:specs" not in self.kv:
            try:
                from ray_tpu._private import slo as slo_mod

                slo_mod.parse_specs(env_specs)
                self.kv["slo:specs"] = env_specs.encode()
            except (ValueError, TypeError) as e:
                logger.warning("RAY_TPU_SLO_SPECS rejected: %s", e)

        asyncio.get_running_loop().create_task(self._scheduler_loop())
        asyncio.get_running_loop().create_task(self._idle_reaper_loop())
        asyncio.get_running_loop().create_task(self._failure_detector_loop())
        asyncio.get_running_loop().create_task(self._persist_loop())
        asyncio.get_running_loop().create_task(self._memory_monitor_loop())
        asyncio.get_running_loop().create_task(self._workload_observer_loop())
        logger.info("head server listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self):
        self._shutdown = True
        if self._shard_server is not None:
            self._shard_server.stop()
        if self._storage is not None:
            try:
                async with self._compact_lock:
                    self._storage.compact(self._snapshot_tables())
            except Exception:  # noqa: BLE001
                logger.exception("final WAL compaction failed at shutdown")
        # kill all worker processes we know about
        for w in list(self.workers.values()):
            try:
                os.kill(w.pid, 15)
            except OSError:
                pass
        if self._zygote is not None:
            self._zygote.stop()
        for conn in list(self._conns.values()):
            conn.close()
        if self._server:
            self._server.close()
        try:
            self.object_agent.stop()
        except Exception:  # noqa: BLE001
            logger.debug("object agent stop failed at shutdown", exc_info=True)
        try:
            self._store.close()
        except Exception:  # noqa: BLE001
            logger.debug("store close failed at shutdown", exc_info=True)

    # ---------------------------------------------- table persistence (WAL)

    def _mark_tables_dirty(self):
        self._tables_dirty = True

    def _wal(self, *record):
        """Append one table mutation to the WAL (never fatal)."""
        if self._storage is None:
            return
        try:
            self._storage.append(record)
        except Exception:  # noqa: BLE001
            # losing a WAL record silently costs durability on the NEXT
            # restart; say so loudly even though the live tables are intact
            logger.exception("WAL append failed; record dropped: %r", record[:1])

    def _wal_locs(self, oid: bytes):
        """Idempotent location upsert after any directory mutation."""
        self._wal("loc=", bytes(oid), sorted(self.object_locations.get(oid, ())))

    def _snapshot_tables(self) -> dict:
        detached = []
        for actor in self.actors.values():
            if actor.detached and actor.state != ACTOR_DEAD:
                detached.append(actor.creation_spec.to_wire())
        pgs = [
            (pg.pg_id, pg.bundles, pg.strategy, pg.name)
            for pg in self.pgs.values()
            if pg.state != "REMOVED"
        ]
        return {
            # the runtime chaos plan ("chaos:plan", written by h_chaos_ctrl
            # outside the WAL) must not ride the snapshot: a restarted head
            # comes back fault-free unless the env re-arms it
            "kv": {k: v for k, v in self.kv.items() if k != "chaos:plan"},
            "jobs": dict(self.jobs),
            "detached_actors": detached,
            "pgs": pgs,
            "head_node_id": self.head_node_id,
            # object directory + spill registry + lineage: what makes a
            # restarted head able to find / restore / reconstruct objects
            "object_locations": {o: sorted(l) for o, l in self.object_locations.items()},
            "object_spilled": dict(self.object_spilled),
            "lineage": {o: s.to_wire() for o, s in self.lineage.items()},
            "sealed": [o for o, e in self.objects.items() if e[0] == SEALED],
        }

    def _quarantine_wal(self, reason: str):
        """Move the corrupt WAL segments aside so fresh appends start on a
        clean log and the NEXT restart doesn't re-fail on the same bytes."""
        for path in (self._storage.rotated_path, self._storage.wal_path):
            if os.path.exists(path):
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    logger.exception("could not quarantine corrupt WAL %s", path)
        self._record_event(
            "ERROR",
            "head",
            f"WAL corrupt mid-file; recovered from snapshot only ({reason})",
        )

    def _restore_tables(self):
        from ray_tpu.gcs.storage import WalCorruptionError

        try:
            tables, records = self._storage.load()
        except WalCorruptionError as e:
            # mid-file corruption: replaying a reordered suffix can
            # resurrect deleted state — recover the snapshot alone, loudly
            logger.error("WAL replay aborted: %s — falling back to snapshot-only recovery", e)
            tables, records = self._storage.base.load(), []
            self._quarantine_wal(str(e))
        if not tables and not records:
            return
        st, old_heads = self._seed_state_from_tables(tables)
        # replay the WAL over the base state, newest wins.  A record that
        # fails to APPLY is corruption just like a bad crc: skipping it
        # while applying later records reorders state, so the whole replay
        # is abandoned for snapshot-only recovery (positional contract,
        # same as storage._replay_file).
        try:
            self._apply_wal_records(st, records, old_heads)
        except Exception as e:  # noqa: BLE001
            logger.error(
                "WAL record failed to apply — falling back to snapshot-only "
                "recovery",
                exc_info=True,
            )
            self._quarantine_wal(f"unappliable record: {type(e).__name__}: {e}")
            st, old_heads = self._seed_state_from_tables(tables)
            records = []
        self._materialize_restored(st, old_heads, len(records))

    @staticmethod
    def _seed_state_from_tables(tables) -> Tuple[dict, set]:
        st = {
            "kv": {},
            "jobs": {},
            "detached": {},
            "pgs": {},
            "locs": {},
            "spilled": {},
            "lineage": {},
            "sealed": set(),
        }
        old_heads = set()
        if tables and tables.get("head_node_id"):
            old_heads.add(bytes(tables["head_node_id"]))
        if tables:
            st["kv"].update(tables.get("kv", {}))
            st["jobs"].update(tables.get("jobs", {}))
            for wire in tables.get("detached_actors", []):
                st["detached"][bytes(TaskSpec.from_wire(wire).actor_id)] = wire
            for pg_id, bundles, strategy, name in tables.get("pgs", []):
                st["pgs"][bytes(pg_id)] = (bundles, strategy, name)
            st["locs"].update(
                {bytes(o): set(l) for o, l in tables.get("object_locations", {}).items()}
            )
            st["spilled"].update(
                {bytes(o): tuple(v) for o, v in tables.get("object_spilled", {}).items()}
            )
            st["lineage"].update(
                {bytes(o): w for o, w in tables.get("lineage", {}).items()}
            )
            st["sealed"].update(bytes(o) for o in tables.get("sealed", []))
        return st, old_heads

    @staticmethod
    def _apply_wal_records(st: dict, records: List[Tuple], old_heads: set):
        for rec in records:
            kind = rec[0]
            if kind == "kv":
                if rec[2] is None:
                    st["kv"].pop(rec[1], None)
                else:
                    st["kv"][rec[1]] = rec[2]
            elif kind == "job":
                st["jobs"][rec[1]] = rec[2]
            elif kind == "dactor":
                if rec[2] is None:
                    st["detached"].pop(bytes(rec[1]), None)
                else:
                    st["detached"][bytes(rec[1])] = rec[2]
            elif kind == "pg":
                if rec[2] is None:
                    st["pgs"].pop(bytes(rec[1]), None)
                else:
                    st["pgs"][bytes(rec[1])] = tuple(rec[2])
            elif kind == "seal":
                st["sealed"].add(bytes(rec[1]))
            elif kind == "loc=":
                locs = {bytes(x) for x in rec[2]}
                if locs:
                    st["locs"][bytes(rec[1])] = locs
                else:
                    st["locs"].pop(bytes(rec[1]), None)
            elif kind == "spill":
                if rec[2] is None:
                    st["spilled"].pop(bytes(rec[1]), None)
                else:
                    st["spilled"][bytes(rec[1])] = tuple(rec[2])
            elif kind == "lineage":
                if rec[2] is None:
                    st["lineage"].pop(bytes(rec[1]), None)
                else:
                    st["lineage"][bytes(rec[1])] = rec[2]
            elif kind == "obj-":
                oid = bytes(rec[1])
                st["locs"].pop(oid, None)
                st["spilled"].pop(oid, None)
                st["sealed"].discard(oid)
            elif kind == "head":
                old_heads.add(bytes(rec[1]))
            elif kind == "boot":
                pass  # incarnation breadcrumb (head_meta.json is authoritative)
            else:
                raise ValueError(f"unknown WAL record kind {kind!r}")

    def _materialize_restored(self, st: dict, old_heads: set, n_records: int):
        # the CURRENT head id is not "old" even if a prior boot WAL'd it:
        # a restarted head reuses its predecessor's identity (head_meta)
        old_heads = {h for h in old_heads if h != self.head_node_id}
        self.kv.update(st["kv"])
        self.jobs.update(st["jobs"])
        for wire in st["detached"].values():
            spec = TaskSpec.from_wire(wire)
            if spec.actor_id in self.actors:
                continue
            actor = ActorInfo(spec)
            actor.owner_conn_id = -1  # detached: owned by the cluster
            self.actors[spec.actor_id] = actor
            if spec.name:
                self.named_actors[(spec.namespace, spec.name)] = spec.actor_id
            self._actor_mirror.upsert(
                spec.actor_id,
                state=ACTOR_PENDING,
                name=spec.name,
                namespace=spec.namespace,
                creation_spec=wire,
                direct_addr="",
                death_cause="",
            )
            for oid in spec.return_object_ids():
                self._object_entry(oid)
            if self._recovery is not None:
                # live-recovery: the actor's worker may still be ALIVE and
                # mid-redial — park the creation; a worker re-attach claims
                # it, and _finish_recovery requeues the unclaimed rest
                self._recovery["unclaimed_actors"].add(bytes(spec.actor_id))
                continue
            # old worker processes died with the previous head; re-run the
            # creation task on a fresh worker (actor restart semantics)
            entry = TaskEntry(spec, -1)
            self.tasks[spec.task_id] = entry
            self.task_queue.append(entry)
        for pg_id, (bundles, strategy, name) in st["pgs"].items():
            if pg_id not in self.pgs:
                self.pgs[pg_id] = PlacementGroupInfo(pg_id, bundles, strategy, name)
        for oid, locs in st["locs"].items():
            # nodes re-register with their prior ids; stale entries for
            # nodes that never come back are pruned at the end of the
            # recovery grace window (or skipped by the pull path).
            # Entries on a PRIOR head incarnation are gone for good (that
            # head's store segment was recreated); entries on THIS head's
            # own node survive when the segment was attached, not rebuilt.
            locs = {n for n in locs if n not in old_heads}
            if not self._store_preserved:
                locs.discard(self.head_node_id)
            if locs:
                self.object_locations[oid] = set(locs)
        for oid, (nid, spath) in st["spilled"].items():
            # spill FILES survive head restarts; files spilled by the old
            # head process are served by THIS head (same session dir)
            if bytes(nid) in old_heads:
                nid = self.head_node_id
            self.object_spilled[oid] = (bytes(nid), spath)
        for oid, wire in st["lineage"].items():
            try:
                spec = TaskSpec.from_wire(wire)
            except Exception:  # noqa: BLE001
                logger.warning(
                    "dropping undecodable lineage entry for %s during replay",
                    oid.hex()[:16],
                    exc_info=True,
                )
                continue
            self._record_lineage(spec, len(repr(wire)))
        for oid in (
            st["sealed"] | set(st["locs"]) | set(st["spilled"]) | set(st["lineage"])
        ):
            e = self._object_entry(oid)
            e[0] = SEALED
            self._obj_mirror.seal(oid)
        logger.info(
            "restored GCS tables: %d kv, %d detached actors, %d pgs, "
            "%d object locations, %d spilled, %d lineage entries "
            "(%d WAL records replayed)",
            len(st["kv"]),
            len(st["detached"]),
            len(st["pgs"]),
            len(st["locs"]),
            len(st["spilled"]),
            len(st["lineage"]),
            n_records,
        )
        # fold everything into a fresh base so the next restart replays a
        # short WAL
        try:
            self._storage.compact(self._snapshot_tables())
        except Exception:  # noqa: BLE001
            logger.exception("post-replay WAL compaction failed")

    async def _persist_loop(self):
        """Compaction pacing: the WAL already made every mutation durable;
        this loop just folds it into the base snapshot when it grows (or
        periodically while dirty, bounding replay length).  Only phase 1
        (serialize + WAL rotation) runs on the loop — snapshot file IO and
        fsync happen in a thread so head RPCs never stall behind them; the
        batched-fsync flusher also rides this loop's tick."""
        last_compact = time.time()
        while not self._shutdown:
            await asyncio.sleep(0.5)
            try:
                # bound the batched-fsync window; in a thread so head RPCs
                # never wait on disk, under the lock so a concurrent
                # begin_compact can't close the fd mid-fsync
                async with self._compact_lock:
                    await asyncio.to_thread(self._storage.sync)
            except Exception:  # noqa: BLE001
                logger.exception("batched WAL fsync failed; retrying next tick")
            grown = self._storage.wal_bytes > 4 * (1 << 20)
            periodic = self._tables_dirty and time.time() - last_compact > 10.0
            if not (grown or periodic):
                continue
            self._tables_dirty = False
            last_compact = time.time()
            try:
                async with self._compact_lock:
                    # phase 1 ON the loop: the snapshot must be consistent
                    # with the WAL rotation point w.r.t. concurrent appends
                    snapshot = self._storage.begin_compact(self._snapshot_tables())
                    await asyncio.to_thread(self._storage.finish_compact, snapshot)
            except Exception:
                logger.exception("GCS compaction failed")

    # ------------------------------------- head FT: recovery + reattachment

    def _note_done(self, tid: bytes):
        """Remember a processed TASK_DONE (bounded) so a reattached
        worker's replay of the same completion is dropped, not re-applied."""
        tid = bytes(tid)
        if tid in self._recent_dones:
            return
        if len(self._recent_dones_fifo) == self._recent_dones_fifo.maxlen:
            self._recent_dones.discard(self._recent_dones_fifo[0])
        self._recent_dones_fifo.append(tid)
        self._recent_dones.add(tid)

    def _resubmit_is_duplicate(self, spec: TaskSpec) -> bool:
        """Idempotent resubmit check: the task id IS the idempotency key.
        A resubmitted spec is a duplicate if the task is still tracked
        (re-announced by its reattached worker), was already seen
        completing, or every return object already sealed/errored (the
        WAL'd commit point)."""
        if spec.task_id in self.tasks:
            return True
        if bytes(spec.task_id) in self._recent_dones:
            return True
        oids = spec.return_object_ids()
        if oids and all(
            self.objects.get(oid, (PENDING,))[0] in (SEALED, ERRORED)
            for oid in oids
        ):
            return True
        return False

    async def _recovery_window(self):
        rec = self._recovery
        if rec is None:
            return
        await asyncio.sleep(max(0.0, rec["deadline"] - time.time()))
        try:
            await self._finish_recovery()
        except Exception:  # noqa: BLE001
            logger.exception("recovery reconciliation failed; resuming dispatch anyway")
            self._recovery = None
            self._kick_scheduler()

    async def _finish_recovery(self):
        """Close the grace window: everything re-announced stays; state
        not reconfirmed is declared dead through the EXISTING machinery —
        detached-actor creations requeue (fault FSM), unclaimed driver
        actors die like their owner exited, stale object locations prune
        so lineage/spill recovery applies, parked calls and resubmits
        flow with idempotent dedupe."""
        rec, self._recovery = self._recovery, None
        if rec is None:
            return
        reaped = {"actors": 0, "owners": 0, "locations": 0, "spills": 0}
        # 1. restored detached actors nobody reclaimed: their workers are
        #    gone — re-run creation on a fresh worker (cold-restart path)
        for aid in rec["unclaimed_actors"]:
            actor = self.actors.get(aid)
            if actor is None or actor.state != ACTOR_PENDING or actor.worker_id:
                continue
            entry = TaskEntry(actor.creation_spec, -1)
            self.tasks[actor.creation_spec.task_id] = entry
            self.task_queue.append(entry)
            reaped["actors"] += 1
            self._record_event(
                "WARNING",
                "head",
                "ghost reaped: detached actor never re-announced; "
                "respawning through the restart FSM",
                actor_id=aid.hex(),
            )
        # 2. worker-announced non-detached actors whose owner driver never
        #    re-attached: same fate as an owner that exited.  Per-actor
        #    isolation: one malformed entry must not abandon the parked
        #    resubmit/call drains below (their senders were acked
        #    {parked: true} and will never re-send)
        for actor in list(self.actors.values()):
            if actor.owner_conn_id == -2 and not actor.detached:
                claim = self._owner_claims.get(actor.actor_id)
                if claim is not None:
                    actor.owner_conn_id = claim  # late claim application
                    continue
                reaped["owners"] += 1
                self._owner_claims.pop(actor.actor_id, None)
                try:
                    await self._destroy_actor(
                        actor, "owner driver never re-attached after head restart"
                    )
                except Exception:  # noqa: BLE001
                    logger.exception("orphan-owner reap failed; continuing reconcile")
        # surviving claims are KEPT: a worker whose redial outlasts the
        # grace window still binds its announced actors to the right
        # owner conn instead of the -2 sentinel (which nothing ever reaps)
        # 3. object locations / spill entries on nodes that never came
        #    back: prune so gets fall through to spill-restore / lineage
        #    reconstruction instead of hanging on a dead copy
        for oid, locs in list(self.object_locations.items()):
            dead = {n for n in locs if n not in self.nodes}
            if dead:
                locs -= dead
                reaped["locations"] += 1
                if not locs:
                    del self.object_locations[oid]
                self._wal_locs(oid)
        for oid, (nid, _path) in list(self.object_spilled.items()):
            if bytes(nid) not in self.nodes:
                del self.object_spilled[oid]
                self._wal("spill", bytes(oid), None)
                reaped["spills"] += 1
        # 4. actor calls that raced the reconciliation: their actors are
        #    either re-announced (push) or truly dead (typed error)
        calls, self._recovery_actor_calls = self._recovery_actor_calls, []
        for spec in calls:
            try:
                await self._submit_actor_task(spec)
            except Exception:  # noqa: BLE001
                logger.exception("parked actor call failed during reconcile")
        # 5. lease restores for still-absent workers stay parked in
        #    _pending_lease_restores — each worker's own (possibly late)
        #    reattach drains its entries
        # 6. parked resubmits: enqueue only what no surviving peer owns
        resubs, self._recovery_resubmits = self._recovery_resubmits, []
        deduped = 0
        for cid, wire in resubs:
            try:
                spec = TaskSpec.from_wire(wire)
                if self._resubmit_is_duplicate(spec):
                    deduped += 1
                    continue
                await self.h_submit_task(cid, None, {"spec": wire})
            except Exception:  # noqa: BLE001
                logger.exception("parked resubmit failed during reconcile")
        duration = time.time() - rec["started"]
        self.last_recovery = {
            "at": time.time(),
            "duration_s": duration,
            "incarnation": self.incarnation,
            "reattached": dict(self._reattach_stats),
            "reaped": reaped,
            "resubmits": {"received": len(resubs), "deduped": deduped},
        }
        self._set_gauge(
            "ray_tpu_head_recovery_seconds",
            "duration of the last head recovery grace window",
            {},
            duration,
        )
        self._record_event(
            "INFO",
            "head",
            "recovery reconcile complete: "
            f"{self._reattach_stats['nodes']} nodes / "
            f"{self._reattach_stats['workers']} workers / "
            f"{self._reattach_stats['drivers']} drivers re-attached, "
            f"{self._reattach_stats['actors']} actors + "
            f"{self._reattach_stats['tasks']} running tasks reclaimed; "
            f"reaped {reaped['actors']} actors, {reaped['owners']} orphaned "
            f"owners, {reaped['locations']} stale locations; "
            f"{deduped}/{len(resubs)} resubmits deduped",
            **{f"reattached_{k}": v for k, v in self._reattach_stats.items()},
        )
        logger.info("head recovery complete in %.2fs: %s", duration, self.last_recovery)
        self._kick_scheduler()

    def _restore_lease(self, cid: int, l: dict):
        """Re-establish a holder-announced worker lease after a restart.
        The lease's task flow never stopped (pushes ride the holder↔worker
        direct conn) — this only restores the head's resource hold so the
        scheduler doesn't double-book the leased worker."""
        wid = bytes(l.get("worker_id") or b"")
        w = self.workers.get(wid)
        if w is None:
            # the leased worker is still mid-redial: park the claim; the
            # worker's own reattach drains it (silently dropping it would
            # let the scheduler double-book the worker the holder is
            # still pushing lease tasks to)
            self._pending_lease_restores.setdefault(wid, []).append((cid, l))
            return
        if w.lease is not None:
            # already held (duplicate announce, or a same-head reattach of
            # a lease the head never forgot): REBIND it to the holder's new
            # conn, or the old conn's late EOF would release a lease the
            # reattached holder is still pushing on
            old_cid = w.lease.get("cid")
            if old_cid != cid:
                lid = bytes(w.lease.get("lease_id") or b"")
                w.lease["cid"] = cid
                if old_cid is not None:
                    self._leases_by_conn.get(old_cid, set()).discard(lid)
                self._leases_by_conn.setdefault(cid, set()).add(lid)
            return
        res = {str(k): float(v) for k, v in (l.get("resources") or {}).items()}
        node = self.nodes.get(w.node_id)
        if node is None:
            return
        node.acquire(res)
        node.mark_busy(w)
        lid = bytes(l.get("lease_id") or b"")
        w.lease = {
            "lease_id": lid,
            "cid": cid,
            "resources": res,
            "priority": int(l.get("priority", 1)),
            "via": "head",
            "granted_at": time.time(),
            "revoking": False,
        }
        self.leases[lid] = wid
        self._leases_by_conn.setdefault(cid, set()).add(lid)
        self._reattach_stats["leases"] += 1

    async def h_reattach(self, cid, conn, p):
        """A live peer redialed after a head restart and re-announces what
        it holds.  Role-tagged; every branch is idempotent (a retried
        reattach re-applies cleanly)."""
        role = str(p.get("role", ""))
        if role == "node":
            nid = bytes(p["node_id"])
            node = self.nodes.get(nid)
            if node is None:
                node = NodeInfo(
                    nid, conn, p["resources"], p["store_path"], sched=self.sched
                )
                self.nodes[nid] = node
            else:
                node.conn = conn
                node.alive = True
            node.address = p.get("address", "")
            node.transfer_addr = p.get("transfer_addr", "")
            if p.get("metrics_addr"):
                node.labels["metrics_addr"] = p["metrics_addr"]
            if p.get("dispatch_addr"):
                node.labels["dispatch_addr"] = p["dispatch_addr"]
            self._conn_kind[cid] = "raylet"
            self._conn_node[cid] = nid
            self._last_beat[cid] = time.time()
            self._reattach_stats["nodes"] += 1
            self._record_event(
                "INFO",
                "head",
                "node re-attached after head restart",
                node_id=nid.hex(),
                objects=int(p.get("num_objects", 0)),
            )
            self._kick_scheduler()
            return {
                "ok": True,
                "head_node_id": self.head_node_id,
                "incarnation": self.incarnation,
            }
        if role == "worker":
            nid = bytes(p["node_id"])
            node = self.nodes.get(nid)
            if node is None:
                # its raylet hasn't re-registered yet: ask the worker to
                # retry within its window instead of failing it
                return {"ok": False, "retry": True, "reason": "node not re-attached yet"}
            wid = bytes(p["worker_id"])
            w = self.workers.get(wid)
            if w is None:
                w = WorkerInfo(
                    wid, nid, conn, int(p.get("pid", 0)), has_tpu=bool(p.get("has_tpu"))
                )
                self.workers[wid] = w
                node.workers[wid] = w
            else:
                w.conn = conn
            if p.get("direct_addr"):
                host = str(node.transfer_addr or "127.0.0.1:0").rsplit(":", 1)[0]
                port = str(p["direct_addr"]).rsplit(":", 1)[-1]
                w.direct_addr = f"{host or '127.0.0.1'}:{port}"
            self._conn_kind[cid] = "worker"
            self._conn_worker[cid] = wid
            self._last_beat[cid] = time.time()
            actor_wire = p.get("actor")
            if actor_wire:
                await self._reclaim_actor(w, node, actor_wire, p)
            elif w.actor_id is None and not w.running_tasks:
                node.mark_idle(w)
            for wire in p.get("running", []):
                spec = TaskSpec.from_wire(wire)
                existing = self.tasks.get(spec.task_id)
                if existing is not None:
                    if existing.state == "QUEUED":
                        # _on_worker_dead requeued it when this worker's
                        # old conn EOF'd, but the worker survived and is
                        # STILL running it: cancel the duplicate retry or
                        # the scheduler double-executes the task
                        try:
                            self.task_queue.remove(existing)
                        except ValueError:
                            pass
                        self.tasks.pop(spec.task_id, None)
                    else:
                        continue
                entry = TaskEntry(spec, -1)
                entry.state = "RUNNING"
                entry.worker_id = wid
                entry.node_id = nid
                self.tasks[spec.task_id] = entry
                w.running_tasks.add(spec.task_id)
                for oid in spec.return_object_ids():
                    self._object_entry(oid)
                if spec.task_type == NORMAL_TASK:
                    node.mark_busy(w)
                    node.acquire(self._task_resources(spec))
                elif spec.task_type == ACTOR_CREATION_TASK:
                    # the crash raced this creation mid-__init__: the dead
                    # head acked CREATE_ACTOR (so the driver will not
                    # re-issue it) but the instance wasn't up yet, so the
                    # worker's announce carries only the running spec.
                    # Materialize the FSM entry NOW or the imminent
                    # TASK_DONE has no ActorInfo to flip ALIVE and the
                    # live actor would be unreachable forever.
                    aid2 = bytes(spec.actor_id)
                    actor2 = self.actors.get(aid2)
                    if actor2 is None:
                        actor2 = ActorInfo(spec)
                        actor2.owner_conn_id = (
                            -1
                            if spec.detached
                            else self._owner_claims.get(aid2, -2)
                        )
                        self.actors[aid2] = actor2
                        if spec.name:
                            self.named_actors[(spec.namespace, spec.name)] = aid2
                        if spec.detached:
                            self._wal("dactor", aid2, wire)
                            self._mark_tables_dirty()
                        # creation-time hold (implicit CPU included):
                        # _release_creation_cpu gives the implicit share
                        # back when TASK_DONE flips it ALIVE
                        node.acquire(dict(spec.resources or {"CPU": 1.0}))
                    actor2.worker_id = wid
                    actor2.node_id = nid
                    w.dedicated = True
                    w.actor_id = aid2
                    node.mark_busy(w)
                    if self._recovery is not None:
                        self._recovery["unclaimed_actors"].discard(aid2)
                self._reattach_stats["tasks"] += 1
            # a worker-hosted actor can OWN actors (the serve controller
            # owns its replicas) and hold cached leases, exactly like a
            # driver — its claims must land or reconciliation owner-reaps
            # otherwise-healthy actors
            self._apply_reattach_claims(cid, p)
            # lease claims parked while THIS worker was mid-redial drain
            # now (holder conn must still be live — a dead holder's
            # release path already ran and would never reclaim the hold)
            for hcid, l in self._pending_lease_restores.pop(wid, []):
                if hcid in self._conns:
                    self._restore_lease(hcid, l)
            self._reattach_stats["workers"] += 1
            self._kick_scheduler()
            return {
                "ok": True,
                "store_path": node.store_path,
                # False only for head-node peers when the surviving segment
                # was unusable and recreated: their mmaps point at the dead
                # inode and must re-attach (split-brain otherwise)
                "store_preserved": bool(
                    self._store_preserved or nid != self.head_node_id
                ),
                "shard_addrs": self.shard_addrs,
                "incarnation": self.incarnation,
            }
        if role == "driver":
            self._conn_kind[cid] = "driver"
            job_id = p.get("job_id", b"")
            if job_id not in self.jobs:
                self.jobs[job_id] = {
                    "started_at": time.time(),
                    "driver_pid": p.get("pid", 0),
                }
                self._wal("job", job_id, self.jobs[job_id])
            self._worker_env.update(p.get("worker_env") or {})
            self._apply_reattach_claims(cid, p)
            self._reattach_stats["drivers"] += 1
            return {
                "ok": True,
                "store_path": self.nodes[self.head_node_id].store_path,
                "store_preserved": self._store_preserved,
                "node_id": self.head_node_id,
                "shard_addrs": self.shard_addrs,
                "incarnation": self.incarnation,
            }
        raise ValueError(f"unknown reattach role {role!r}")

    def _apply_reattach_claims(self, cid: int, p: dict):
        """Bind a reattached peer's ownership claims + held leases: claims
        rebind known actors to the new conn immediately and are retained
        (_owner_claims) for actors whose hosting worker announces later."""
        for aid in p.get("owned_actors", []):
            aid = bytes(aid)
            self._owner_claims[aid] = cid
            actor = self.actors.get(aid)
            if actor is not None and not actor.detached:
                actor.owner_conn_id = cid
        for l in p.get("leases", []):
            self._restore_lease(cid, l)

    async def _reclaim_actor(self, w: WorkerInfo, node: NodeInfo, wire, p: dict):
        """A surviving actor worker re-announced its actor: rebind it into
        the directory as ALIVE with its resources re-acquired, whatever
        the replayed WAL believed."""
        spec = TaskSpec.from_wire(wire)
        aid = bytes(spec.actor_id)
        actor = self.actors.get(aid)
        # the restart FSM may have queued this actor's re-creation before
        # the surviving worker's announce landed (same-head conn sever:
        # _on_worker_dead fired on the old conn's EOF).  A queued creation
        # is cancelled — the live instance wins; one already RUNNING on a
        # fresh worker means the FSM owns the actor now, so the stale
        # instance must NOT be rebound over it.
        creation = self.tasks.get(spec.task_id)
        if creation is not None and creation.spec.task_type == ACTOR_CREATION_TASK:
            if creation.state == "RUNNING" and creation.worker_id != w.worker_id:
                return
            if creation.state == "QUEUED":
                try:
                    self.task_queue.remove(creation)
                except ValueError:
                    pass
                self.tasks.pop(spec.task_id, None)
        fresh = actor is None
        if fresh:
            actor = ActorInfo(spec)
            # -2 = awaiting owner reclaim: a driver reattach claims it
            # (possibly already did — _owner_claims), _finish_recovery
            # destroys the unclaimed rest (owner-exited semantics).
            # Detached actors are cluster-owned as usual.
            if spec.detached:
                actor.owner_conn_id = -1
            else:
                actor.owner_conn_id = self._owner_claims.get(aid, -2)
            self.actors[aid] = actor
            if spec.detached:
                self._wal("dactor", aid, wire)
                self._mark_tables_dirty()
        already_bound = actor.worker_id == w.worker_id and actor.state == ACTOR_ALIVE
        actor.state = ACTOR_ALIVE
        actor.worker_id = w.worker_id
        actor.node_id = node.node_id
        actor.death_log_tail = ""  # forensics from a prior incarnation
        if spec.name:
            self.named_actors[(spec.namespace, spec.name)] = aid
        if p.get("actor_direct_addr"):
            host = str(node.transfer_addr or "127.0.0.1:0").rsplit(":", 1)[0]
            port = str(p["actor_direct_addr"]).rsplit(":", 1)[-1]
            actor.direct_addr = f"{host or '127.0.0.1'}:{port}"
        w.actor_id = aid
        w.dedicated = True
        node.mark_busy(w)
        if not already_bound:
            # lifetime resources were released with the old head's tables;
            # the worker genuinely holds them — force-reacquire
            node.acquire(self._actor_lifetime_resources(spec))
            actor.creation_cpu_released = True
            self._reattach_stats["actors"] += 1
        if self._recovery is not None:
            self._recovery["unclaimed_actors"].discard(aid)
        self._actor_mirror.upsert(
            aid,
            state=ACTOR_ALIVE,
            name=spec.name,
            namespace=spec.namespace,
            creation_spec=wire,
            direct_addr=actor.direct_addr,
            death_cause="",
        )
        await self._publish("actor", {"actor_id": aid, "state": ACTOR_ALIVE})
        # calls queued while the actor was thought PENDING flush now
        calls, actor.pending_calls = actor.pending_calls, []
        for call in calls:
            await self._push_actor_task(actor, call)

    # ----------------------------------------------------------- connections

    async def _on_connection(self, reader, writer):
        conn = Connection(reader, writer)
        self._conn_seq += 1
        cid = self._conn_seq
        self._conns[cid] = conn
        try:
            while not self._shutdown:
                msg_type, rid, payload = await conn.read_frame()
                if conn.dispatch_reply(msg_type, rid, payload):
                    continue
                # serve each request concurrently; handler errors reply ERROR
                asyncio.get_running_loop().create_task(
                    self._handle(cid, conn, msg_type, rid, payload)
                )
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._conns.pop(cid, None)
            self._last_beat.pop(cid, None)
            conn.close()
            await self._on_disconnect(cid, conn)

    async def _handle(self, cid: int, conn: Connection, msg_type: int, rid: int, payload: dict):
        try:
            handler = self._HANDLERS.get(msg_type)
            if handler is None:
                raise ValueError(f"unknown message type {msg_type}")
            result = await handler(self, cid, conn, payload)
            if rid:
                await conn.reply(rid, result or {})
        except Exception as e:  # noqa: BLE001
            logger.exception("handler error for msg %s", msg_type)
            if rid:
                try:
                    await conn.reply(rid, {}, error=f"{type(e).__name__}: {e}")
                except Exception:  # graftlint: disable=silent-except -- error already logged above; the reply transport itself is dead
                    pass

    async def _on_disconnect(self, cid: int, conn: Optional[Connection] = None):
        # leases die with the connection that holds them (driver exit, or
        # a worker whose nested submits cached leases) — unless the holder
        # already reattached and the lease was restored under its NEW cid
        for lid in self._leases_by_conn.pop(cid, set()):
            wid = self.leases.get(lid)
            w = self.workers.get(wid) if wid else None
            if (
                w is not None
                and w.lease is not None
                and w.lease.get("cid", cid) == cid
            ):
                self._release_lease(
                    w, self.nodes.get(w.node_id), reason="holder disconnected"
                )
        kind = self._conn_kind.pop(cid, None)
        self._conn_job.pop(cid, None)
        # device-tier holders served over this conn are gone with it
        if kind in ("worker", "driver"):
            self._device_drop_conn(cid)
        # ownership claims recorded for this conn die with it: a LATER
        # "late claim application" must never rebind an actor to a
        # vanished conn id (conn ids are not reused — that actor would
        # leak forever)
        if kind in ("worker", "driver"):
            for aid in [a for a, c in self._owner_claims.items() if c == cid]:
                del self._owner_claims[aid]
        if kind == "worker":
            wid = self._conn_worker.pop(cid, None)
            w = self.workers.get(wid) if wid else None
            if w is not None and conn is not None and w.conn is not conn:
                return  # reattached on a newer conn: this EOF is stale
            if wid:
                await self._on_worker_dead(wid, "worker process died (connection lost)")
        elif kind == "raylet":
            nid = self._conn_node.pop(cid, None)
            node = self.nodes.get(nid) if nid else None
            if node is not None and conn is not None and node.conn is not conn:
                return  # node reattached on a newer conn: stale EOF
            if nid:
                await self._on_node_dead(nid)
        elif kind == "driver":
            # non-detached actors owned by this driver die with it — but
            # with a reconnect window open the driver may be mid-redial
            # (same-head conn sever): park the orphans behind the window
            # and reap only those never re-claimed
            orphans = [
                actor
                for actor in self.actors.values()
                if actor.owner_conn_id == cid and not actor.detached
            ]
            if not orphans:
                return
            window = RayConfig.head_reconnect_window_s
            if window <= 0:
                for actor in orphans:
                    await self._destroy_actor(actor, "owner driver exited")
                return
            ids = []
            for actor in orphans:
                actor.owner_conn_id = -2  # awaiting owner re-claim
                ids.append(actor.actor_id)
            asyncio.get_running_loop().create_task(
                self._reap_unclaimed_owners(ids, window + 1.0)
            )

    async def _reap_unclaimed_owners(self, actor_ids: List[bytes], delay: float):
        """Reattach-window grace for owner death: destroy only the actors
        whose owner never re-claimed them (reattach rebinds owner_conn_id
        via _apply_reattach_claims, which also records _owner_claims)."""
        await asyncio.sleep(delay)
        for aid in actor_ids:
            actor = self.actors.get(bytes(aid))
            if actor is None or actor.detached or actor.owner_conn_id != -2:
                continue
            claim = self._owner_claims.get(bytes(aid))
            if claim is not None:
                actor.owner_conn_id = claim  # late claim application
                continue
            await self._destroy_actor(
                actor, "owner driver exited (never re-attached)"
            )

    # ------------------------------------------------------ lifecycle: nodes

    async def h_register_node(self, cid, conn, p):
        nid = p["node_id"]
        node = NodeInfo(nid, conn, p["resources"], p["store_path"], sched=self.sched)
        node.address = p.get("address", "")
        node.transfer_addr = p.get("transfer_addr", "")
        if p.get("metrics_addr"):
            node.labels["metrics_addr"] = p["metrics_addr"]
        if p.get("dispatch_addr"):
            # the node's lease agent: clients dial it for node-affine
            # leases (raylet-local dispatch)
            node.labels["dispatch_addr"] = p["dispatch_addr"]
        self.nodes[nid] = node
        self._record_event("INFO", "node", "node registered", node_id=nid.hex())
        self._conn_kind[cid] = "raylet"
        self._conn_node[cid] = nid
        self._kick_scheduler()
        return {"ok": True, "head_node_id": self.head_node_id}

    async def h_register_worker(self, cid, conn, p):
        wid = p["worker_id"]
        nid = p["node_id"]
        node = self.nodes.get(nid)
        if node is None:
            raise ValueError("unknown node")
        w = WorkerInfo(wid, nid, conn, p["pid"], has_tpu=bool(p.get("has_tpu")))
        # where the worker's stdout/stderr land on its node — the
        # LOG_FETCH entity resolution (worker/actor/task → file)
        w.log_file = str(p.get("log_file") or "")
        if w.log_file:
            self._worker_log_src[wid] = {
                "node": nid,
                "path": w.log_file,
                "src": os.path.basename(w.log_file),
            }
            if len(self._worker_log_src) > 8192:
                self._worker_log_src.pop(next(iter(self._worker_log_src)))
        if p.get("direct_addr"):
            # worker binds wildcard; its node's transfer address carries
            # the routable host (same derivation as actor direct addrs)
            host = str(node.transfer_addr or "127.0.0.1:0").rsplit(":", 1)[0]
            port = str(p["direct_addr"]).rsplit(":", 1)[-1]
            w.direct_addr = f"{host or '127.0.0.1'}:{port}"
        self.workers[wid] = w
        node.workers[wid] = w
        node.mark_idle(w)
        node.starting_workers = max(0, node.starting_workers - 1)
        self._conn_kind[cid] = "worker"
        self._conn_worker[cid] = wid
        self._kick_scheduler()
        return {
            "ok": True,
            "store_path": node.store_path,
            "shard_addrs": self.shard_addrs,
        }

    async def h_register_driver(self, cid, conn, p):
        self._conn_kind[cid] = "driver"
        job_id = p.get("job_id", b"")
        # job-scoped log streaming: this driver's "logs" subscription only
        # receives records stamped with ITS job (or stamp-free lines)
        self._conn_job[cid] = job_id
        self.jobs[job_id] = {"started_at": time.time(), "driver_pid": p.get("pid", 0)}
        self._wal("job", job_id, self.jobs[job_id])
        self._mark_tables_dirty()
        self._worker_env.update(p.get("worker_env") or {})
        return {
            "ok": True,
            "store_path": self.nodes[self.head_node_id].store_path,
            "node_id": self.head_node_id,
            "shard_addrs": self.shard_addrs,
        }

    async def h_heartbeat(self, cid, conn, p):
        self._last_beat[cid] = time.time()
        # raylet beats piggyback their node's shm-store occupancy so the
        # head can aggregate cluster memory without an extra RPC plane
        store = p.get("store")
        if store and p.get("node_id") is not None:
            node = self.nodes.get(bytes(p["node_id"]))
            if node is not None:
                node.store_stats = {
                    str(k): float(v) for k, v in store.items()
                }
        return {"ok": True, "t": time.time()}

    async def _failure_detector_loop(self):
        """Missed-beat expiry for raylets and workers: TCP staying open is
        not liveness — a SIGSTOPped process holds its socket forever.
        Analog: reference GcsHeartbeatManager (gcs_heartbeat_manager.h,
        30 missed beats ⇒ dead per ray_config_def.h:56-59)."""
        period = RayConfig.heartbeat_period_ms / 1000.0
        window = period * RayConfig.num_heartbeats_timeout
        while not self._shutdown:
            await asyncio.sleep(period)
            if self._recovery is not None:
                continue  # grace window: peers are mid-redial, not dead
            now = time.time()
            for cid, kind in list(self._conn_kind.items()):
                if kind not in ("raylet", "worker"):
                    continue
                last = self._last_beat.get(cid)
                if last is None:
                    self._last_beat[cid] = now  # grace from first sighting
                    continue
                if now - last <= window:
                    continue
                conn = self._conns.get(cid)
                # a peer that REATTACHed on a newer conn leaves this cid's
                # mappings stale until the old socket EOFs: drop them
                # without reaping the (live, beating-elsewhere) peer
                peer = (
                    self.nodes.get(self._conn_node.get(cid, b""))
                    if kind == "raylet"
                    else self.workers.get(self._conn_worker.get(cid, b""))
                )
                if peer is not None and peer.conn is not conn:
                    self._conn_kind.pop(cid, None)
                    self._conn_node.pop(cid, None)
                    self._conn_worker.pop(cid, None)
                    self._last_beat.pop(cid, None)
                    if conn is not None:
                        conn.close()
                    continue
                if kind == "raylet":
                    nid = self._conn_node.get(cid)
                    logger.warning(
                        "node %s missed heartbeats for %.1fs — declaring dead",
                        nid.hex()[:8] if nid else "?",
                        now - last,
                    )
                    self._conn_kind.pop(cid, None)
                    self._conn_node.pop(cid, None)
                    if nid:
                        await self._on_node_dead(nid)
                else:
                    wid = self._conn_worker.get(cid)
                    logger.warning(
                        "worker %s missed heartbeats for %.1fs — declaring dead",
                        wid.hex()[:8] if wid else "?",
                        now - last,
                    )
                    self._conn_kind.pop(cid, None)
                    self._conn_worker.pop(cid, None)
                    if wid:
                        await self._on_worker_dead(
                            wid, f"missed heartbeats for {now - last:.1f}s"
                        )
                self._last_beat.pop(cid, None)
                if conn is not None:
                    conn.close()

    async def _on_node_dead(self, nid: bytes):
        node = self.nodes.get(nid)
        if node is None or not node.alive:
            return
        node.alive = False
        logger.warning("node %s died", nid.hex()[:8])
        for wid in list(node.workers):
            await self._on_worker_dead(wid, "node died")
        # strip PG bundles on the dead node
        for pg in self.pgs.values():
            for i, bn in enumerate(pg.bundle_nodes):
                if bn == nid:
                    pg.bundle_nodes[i] = None
                    pg.state = "RESCHEDULING"
        del self.nodes[nid]
        self.sched.remove_node(nid)
        # its object copies are gone with its store segment
        for oid, locs in list(self.object_locations.items()):
            if nid in locs:
                locs.discard(nid)
                if not locs:
                    del self.object_locations[oid]
                self._wal_locs(oid)
        await self._publish("node", {"event": "dead", "node_id": nid})
        self._record_event("ERROR", "node", "node died", node_id=nid.hex())
        self._kick_scheduler()

    # ---------------------------------------------------- lifecycle: workers

    async def _on_worker_dead(self, wid: bytes, reason: str):
        w = self.workers.pop(wid, None)
        if w is None:
            return  # already processed (node death then conn drop re-reports)
        self._record_event("WARNING", "worker", f"worker died: {reason}", worker_id=wid.hex())
        node = self.nodes.get(w.node_id)
        if node:
            node.forget_worker(w)
        # a leased worker's death releases the lease's resource hold (the
        # holder notices the conn loss itself and re-routes via the head)
        if w.lease is not None:
            self._release_lease(w, node, reason="worker died")
        logger.info("worker %s dead: %s", wid.hex()[:8], reason)
        # if the process is actually still alive (e.g. declared dead because
        # its node was removed), cut its head connection so it exits instead
        # of lingering as a zombie reporter
        try:
            if w.conn is not None:
                w.conn.close()
        except Exception:  # noqa: BLE001
            logger.debug("closing dead worker connection failed", exc_info=True)
        # fail or retry its running tasks
        for tid in list(w.running_tasks):
            entry = self.tasks.pop(tid, None)
            if entry is None:
                continue
            # only normal tasks hold node resources while running; actor
            # method calls run on the actor's lifetime reservation
            if (
                node
                and entry.state == "RUNNING"
                and not entry.blocked
                and entry.spec.task_type == NORMAL_TASK
            ):
                self._release_task_resources(node, entry.spec)
            if entry.spec.task_type == ACTOR_CREATION_TASK:
                # actor FSM handles restart/destroy below; balance the
                # submit-time pin here (the restart path re-pins)
                self._unpin_args(entry.spec)
                continue
            if entry.spec.task_type == ACTOR_TASK:
                actor = self.actors.get(entry.spec.actor_id)
                if actor is not None and actor.state == ACTOR_PREEMPTED:
                    # graceful preemption: the save fence held the actor
                    # lock, so this pushed call never entered user code —
                    # requeue it for the respawn exactly like a call that
                    # arrives one RPC later, instead of surfacing a policy
                    # eviction to the caller as a WorkerCrashedError
                    actor.pending_calls.append(entry.spec)
                    continue
            if entry.preempted:
                # policy kill, not a fault: requeue on the preemption
                # budget, never the retry budget — and when THAT budget is
                # spent, seal a typed PreemptedError so callers can tell
                # "evicted for more important work" from a crash
                entry.preempted = False
                entry.preempt_count += 1
                budget = (
                    entry.spec.max_preemptions
                    if entry.spec.max_preemptions >= 0
                    else RayConfig.task_preemption_budget
                )
                if entry.preempt_count <= budget:
                    entry.state = "QUEUED"
                    entry.worker_id = None
                    entry.node_id = None
                    entry.enqueued_at = time.time()
                    self.tasks[tid] = entry
                    self.task_queue.append(entry)
                    logger.info(
                        "requeueing preempted task %s (preemption %d/%d)",
                        entry.spec.function_name,
                        entry.preempt_count,
                        budget,
                    )
                else:
                    self._unpin_args(entry.spec)
                    await self._seal_error_objects(
                        entry.spec,
                        f"PreemptedError: preempted by higher-priority work "
                        f"(attempt {entry.preempt_count}/{budget})",
                    )
                continue
            if entry.spec.retries_left > 0:
                entry.spec.retries_left -= 1
                entry.state = "QUEUED"
                entry.worker_id = None
                # fresh queue-wait clock: a long-RUNNING task's crash must
                # not instantly qualify it for the starvation boost
                entry.enqueued_at = time.time()
                self.tasks[tid] = entry  # stays tracked across the retry
                self.task_queue.append(entry)
                logger.info("retrying task %s (%d retries left)", entry.spec.function_name, entry.spec.retries_left)
            else:
                self._unpin_args(entry.spec)
                await self._seal_error_objects(
                    entry.spec,
                    f"WorkerCrashedError: worker died while running "
                    f"{entry.spec.function_name or entry.spec.method_name}: {reason}",
                )
        # actor hosted on this worker?
        if w.actor_id is not None:
            actor = self.actors.get(w.actor_id)
            if actor is not None:
                await self._on_actor_worker_dead(actor, reason)
        self._retire_worker_metrics(wid)
        self._kick_scheduler()

    def _retire_worker_metrics(self, wid: bytes):
        """Fold a dead worker's per-process metric series into one durable
        ``:retired`` series per (metric, tags) and drop the per-worker
        keys — without this, worker churn grows the metrics: namespace
        (and every scrape payload) by one immortal record per dead
        process.  Counters and histograms keep their totals; a dead
        worker's gauge is a stale point-in-time reading and dies with it."""
        import json as _json

        from ray_tpu.util import metrics as metrics_mod

        suffix = ":" + wid.hex()[:12]
        for key in [
            k for k in self.kv if k.startswith("metrics:") and k.endswith(suffix)
        ]:
            blob = self.kv.pop(key)
            try:
                rec = _json.loads(blob)
            except (ValueError, TypeError):
                continue
            if rec.get("kind") == "gauge":
                continue
            rkey = key[: -len(suffix)] + ":retired"
            cur_blob = self.kv.get(rkey)
            if cur_blob is not None:
                try:
                    cur = _json.loads(cur_blob)
                    metrics_mod.merge_records(cur, rec)
                    rec = cur
                except (ValueError, TypeError):
                    pass  # corrupt retired record: replace it outright
            self.kv[rkey] = _json.dumps(rec).encode()

    async def _on_actor_worker_dead(self, actor: ActorInfo, reason: str):
        if actor.state == ACTOR_DEAD:
            return
        node = self.nodes.get(actor.node_id) if actor.node_id else None
        if node:
            # a death MID-CREATION still holds the implicit creation CPU
            self._release_creation_cpu(actor, node, actor.creation_spec)
            node.release(self._actor_lifetime_resources(actor.creation_spec))
        # crash forensics: snapshot the victim's recent lines NOW — the
        # worker binding is cleared just below, after which neither
        # _destroy_actor here nor a later exhausted-restart death can
        # resolve worker → log file
        actor.death_log_tail = (
            self._with_log_tail(actor.worker_id) or actor.death_log_tail
        )
        actor.worker_id = None
        actor.node_id = None
        actor.direct_addr = ""
        # the death event is where a preemption reservation ends: the
        # forced-escalation path keeps the actor reserved in _preempting
        # until here so a concurrent victim scan can't re-preempt the
        # ALIVE-again actor and turn a budget-charged fault kill into an
        # uncharged graceful park
        self._preempting.discard(actor.actor_id)
        if actor.state == ACTOR_PREEMPTED:
            # policy eviction, checkpoint already saved: park until
            # capacity returns (the scheduler loop re-admits) — the
            # fault-restart budget is NOT charged; this death is the
            # graceful release the preemption protocol asked for
            actor.creation_cpu_released = False
            self._preempted_parked.setdefault(actor.actor_id, time.time())
            self._actor_mirror.upsert(
                actor.actor_id, state=ACTOR_PREEMPTED, direct_addr=""
            )
            self._record_event(
                "WARNING",
                "preempt",
                "actor preempted: checkpointed and released; parked for "
                "re-admission",
                actor_id=actor.actor_id.hex(),
            )
            await self._publish(
                "actor", {"actor_id": actor.actor_id, "state": ACTOR_PREEMPTED}
            )
            self._kick_scheduler()
            return
        if actor.restarts_used < actor.max_restarts or actor.max_restarts == -1:
            actor.restarts_used += 1
            self._requeue_actor_creation(actor)
            logger.info(
                "restarting actor %s (%d/%s)",
                actor.actor_id.hex()[:8],
                actor.restarts_used,
                actor.max_restarts,
            )
            self._record_event(
                "WARNING",
                "actor",
                f"actor restarting ({actor.restarts_used}/{actor.max_restarts})",
                actor_id=actor.actor_id.hex(),
            )
            await self._publish("actor", {"actor_id": actor.actor_id, "state": ACTOR_RESTARTING})
        else:
            # terminal: the death cause carries the restart accounting so
            # the client-side RayActorError says HOW the budget was spent,
            # not just that the actor is gone
            await self._destroy_actor(
                actor,
                f"{reason} (restarts exhausted: "
                f"{actor.restarts_used}/{actor.max_restarts})",
            )
        self._kick_scheduler()

    def _requeue_actor_creation(self, actor: ActorInfo):
        """Queue a fresh creation incarnation through the restart FSM —
        shared by fault restarts and preemption re-admission so the two
        paths cannot drift.  The new incarnation acquires CPU afresh, and
        args are re-pinned exactly like a fresh submit: the restarted
        creation task's h_task_done will unpin again (without this,
        restart underflows the arg refcounts and deletes live objects)."""
        actor.state = ACTOR_RESTARTING
        self._actor_mirror.upsert(
            actor.actor_id, state=ACTOR_RESTARTING, direct_addr=""
        )
        actor.creation_cpu_released = False
        spec = actor.creation_spec
        self._pin_args(spec)
        entry = TaskEntry(spec, -1)
        self.tasks[spec.task_id] = entry
        self.task_queue.append(entry)

    async def _destroy_actor(self, actor: ActorInfo, reason: str):
        if actor.detached:
            self._wal("dactor", bytes(actor.actor_id), None)
            self._mark_tables_dirty()
        if actor.state == ACTOR_DEAD:
            return
        # a destroy racing a preemption wins: drop the parking-lot entry
        # (no respawn), the in-flight reservation, and the saved
        # checkpoint (nobody will restore it)
        self._preempted_parked.pop(actor.actor_id, None)
        self._preempting.discard(actor.actor_id)
        ckpt_key = f"actor_ckpt:{actor.actor_id.hex()}"
        if ckpt_key in self.kv:
            del self.kv[ckpt_key]
            self._wal("kv", ckpt_key, None)
        actor.state = ACTOR_DEAD
        actor.death_cause = reason
        # crash forensics: snapshot the victim worker's recent lines
        # (the ring keeps rolling for the worker's successor); a worker-
        # death path already snapshotted in _on_actor_worker_dead before
        # it cleared the binding — keep that copy.  Every seal of this
        # actor's calls — current and future — carries the tail.
        if not actor.death_log_tail:
            actor.death_log_tail = self._with_log_tail(actor.worker_id)
        if not reason.startswith(("ray.kill", "owner driver")):
            # intentional teardown is not an error; faults and exhausted
            # restart budgets are
            tail_lines: List[str] = []
            if actor.death_log_tail:
                import json as _json

                try:
                    tail_lines = _json.loads(
                        actor.death_log_tail[len(_log_plane.LOG_TAIL_MARKER) :]
                    )
                except (ValueError, TypeError):
                    tail_lines = []
            self._note_error_record(
                {
                    "signature": (
                        f"ActorDeath:{actor.creation_spec.name}:"
                        f"{reason.split('(')[0].strip()[:120]}"
                    ),
                    "kind": "actor_death",
                    "exc_type": "ActorDiedError",
                    "message": reason,
                    "name": actor.creation_spec.name,
                    "actor_id": actor.actor_id.hex(),
                    "node_id": actor.node_id.hex() if actor.node_id else "",
                    "log_tail": tail_lines,
                }
            )
        self._actor_mirror.upsert(
            actor.actor_id, state=ACTOR_DEAD, death_cause=reason, direct_addr=""
        )
        logger.info("actor %s dead: %s", actor.actor_id.hex()[:8], reason)
        self._record_event("ERROR", "actor", f"actor dead: {reason}", actor_id=actor.actor_id.hex())
        if actor.name:
            self.named_actors.pop((actor.namespace, actor.name), None)
            self._actor_mirror.drop_name(actor.namespace, actor.name)
        # fail queued calls
        for spec in actor.pending_calls:
            self._unpin_args(spec)
            await self._seal_error_objects(
                spec, f"RayActorError: {reason}{actor.death_log_tail}"
            )
        actor.pending_calls.clear()
        # drop queued creation / calls in the scheduler queue (balancing
        # their submit-time arg pins)
        dropped = [e for e in self.task_queue if e.spec.actor_id == actor.actor_id]
        self.task_queue = [
            e
            for e in self.task_queue
            if not (e.spec.actor_id == actor.actor_id)
        ]
        for e in dropped:
            self.tasks.pop(e.spec.task_id, None)
            self._unpin_args(e.spec)
        if actor.worker_id:
            w = self.workers.get(actor.worker_id)
            if w is not None:
                w.actor_id = None
                # reaches remote hosts too (raylet kill_worker directive)
                self._kill_worker_process(w, 15)
            node = self.nodes.get(actor.node_id) if actor.node_id else None
            if node:
                self._release_creation_cpu(actor, node, actor.creation_spec)
                node.release(self._actor_lifetime_resources(actor.creation_spec))
            actor.worker_id = None
        await self._publish("actor", {"actor_id": actor.actor_id, "state": ACTOR_DEAD, "reason": reason})

    # --------------------------------------------------------------- objects

    def _object_entry(self, oid: bytes) -> List:
        e = self.objects.get(oid)
        if e is None:
            e = [PENDING, None]
            self.objects[oid] = e
        return e

    async def _seal_object(self, oid: bytes):
        e = self._object_entry(oid)
        e[0] = SEALED
        self._obj_mirror.seal(oid)  # wake shard-side waiters too
        self._wal("seal", bytes(oid))
        for fut in self.object_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(e)

    async def _seal_error_objects(self, spec: TaskSpec, error: str):
        """Mark every return object of a failed task as errored; waiters get
        the error string and raise client-side."""
        for oid in spec.return_object_ids():
            e = self._object_entry(oid)
            e[0] = ERRORED
            e[1] = error
            self._obj_mirror.error(oid, error)
            for fut in self.object_waiters.pop(oid, []):
                if not fut.done():
                    fut.set_result(e)

    def _add_location(self, oid: bytes, node_id: Optional[bytes]):
        # only live nodes can serve copies; a zombie worker on a removed
        # node must not pollute the directory
        if node_id and bytes(node_id) in self.nodes:
            self.object_locations.setdefault(oid, set()).add(bytes(node_id))
            self._wal_locs(oid)

    async def h_put_object(self, cid, conn, p):
        nid = p.get("node_id")
        if nid is None:
            nid = self._conn_node.get(cid) or self.head_node_id
        oid = bytes(p["object_id"])
        tier = p.get("tier")
        if tier == "device":
            # metadata-only seal: the payload never left the producer's
            # device store.  The directory gains a pull endpoint instead of
            # a shm location (core/DEVICE_TIER.md).
            self._device_register(cid, conn, nid, oid, p)
            self._pin_contained(oid, p.get("contained") or [])
            self._record_object_meta(cid, oid, p.get("nbytes"), tier="device")
            await self._seal_object(oid)
            return {"ok": True}
        if p.get("device_evicted"):
            # eviction handoff, device→shm rung: the sender spilled its
            # device entry into a shm envelope — drop it as a holder so it
            # is never offered a pull it can no longer serve, and let the
            # shm location recorded below take over
            self._device_drop_holder(oid, p.get("device_addr", ""))
        self._pin_contained(oid, p.get("contained") or [])
        self._record_object_meta(cid, oid, p.get("nbytes"))
        self._add_location(p["object_id"], nid)
        await self._seal_object(p["object_id"])
        return {"ok": True}

    def _record_object_meta(self, cid: int, oid: bytes, nbytes, tier: str = "shm") -> None:
        """Object-accounting sidecar for `ray-tpu summary memory`: who
        sealed it (derived from the sealing connection — workers by id,
        drivers/clients by kind), how big it is, and which tier holds it.
        Device-tier objects report their REAL array nbytes; an eviction
        re-seal overwrites tier to "shm" so a spilled device object is
        never counted in both tiers."""
        wid = self._conn_worker.get(cid)
        owner = (
            bytes(wid).hex()[:12]
            if wid
            else (self._conn_kind.get(cid) or "head")
        )
        self.object_meta[oid] = {
            "owner": owner,
            "nbytes": int(nbytes or 0),
            "tier": tier,
        }

    # ----------------------------------------------------- device tier (head)

    def _device_register(self, cid, conn, nid, oid: bytes, p: dict):
        """Record/refresh a device holder.  First registration comes from
        the producer's put; later ones from consumers that completed a
        pull and now re-serve their subtree — that re-registration is what
        grows the broadcast fan-out tree without the head ever building an
        explicit tree."""
        rec = self.device_objects.setdefault(
            oid, {"meta": dict(p.get("device_meta") or {}), "holders": {}}
        )
        addr = str(p.get("device_addr") or "")
        if addr:
            rec["holders"][addr] = {
                "token": str(p.get("device_token") or ""),
                "cid": cid,
                "conn": conn,
                "node_id": bytes(nid) if nid else self.head_node_id,
                "pulls": [],
            }
        src = p.get("pulled_from")
        if src:
            h = rec["holders"].get(str(src))
            if h is not None and h["pulls"]:
                h["pulls"].pop(0)  # release the fan-out slot this pull held
        self._device_wake(oid)

    def _device_drop_holder(self, oid: bytes, addr: str, failed: bool = False):
        rec = self.device_objects.get(oid)
        if rec is None:
            return
        h = rec["holders"].pop(addr, None)
        if h is not None and failed:
            self._record_event(
                "WARNING",
                "device_tier",
                f"device holder {addr} for {oid.hex()[:16]} failed mid-pull",
            )
        if not rec["holders"]:
            self.device_objects.pop(oid, None)
        self._device_wake(oid)

    def _device_drop_conn(self, cid: int):
        """A worker/driver conn died: every holder endpoint it served is
        gone.  Parked pullers wake and either find a surviving holder or
        fall back to the host plane (shm envelope / spill / lineage)."""
        for oid in list(self.device_objects):
            rec = self.device_objects.get(oid)
            if rec is None:
                continue
            dead = [a for a, h in rec["holders"].items() if h["cid"] == cid]
            for addr in dead:
                rec["holders"].pop(addr, None)
            if dead and not rec["holders"]:
                self.device_objects.pop(oid, None)
            if dead:
                self._device_wake(oid)

    def _device_wake(self, oid: bytes):
        for fut in self._device_slot_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(None)

    def _device_pick_holder(self, oid: bytes) -> Optional[str]:
        """Least-loaded live holder with a free fan-out slot, or None.
        Pull slots decay after 120s — a consumer that died mid-pull (its
        pulled_from release never arrives) must not park the object's
        fan-out forever."""
        rec = self.device_objects.get(oid)
        if not rec:
            return None
        now = time.time()
        fanout = max(1, RayConfig.device_pull_fanout)
        best, best_n = None, None
        for addr, h in rec["holders"].items():
            h["pulls"] = [t for t in h["pulls"] if now - t < 120.0]
            n = len(h["pulls"])
            if n < fanout and (best_n is None or n < best_n):
                best, best_n = addr, n
        return best

    async def _device_directive(
        self, oid: bytes, deadline: Optional[float]
    ) -> Optional[dict]:
        """Resolve a device-tier wait into a pull directive
        ({"state":"sealed","tier":"device","pull":{addr,token,meta}}), or
        None when no holder survives (caller falls back to the host
        plane), or a timeout reply.  When every holder is saturated the
        waiter parks until a slot frees or a new holder joins the tree."""
        while True:
            rec = self.device_objects.get(oid)
            if not rec or not rec["holders"]:
                return None
            addr = self._device_pick_holder(oid)
            if addr is not None:
                h = rec["holders"][addr]
                h["pulls"].append(time.time())
                return {
                    "state": "sealed",
                    "tier": "device",
                    "pull": {"addr": addr, "token": h["token"], "meta": rec["meta"]},
                }
            fut = asyncio.get_running_loop().create_future()
            self._device_slot_waiters.setdefault(oid, []).append(fut)
            rem = None if deadline is None else max(0.001, deadline - time.time())
            try:
                # 1s re-poll backstop: slot decay (dead puller) isn't an
                # event, so a parked waiter must re-evaluate periodically
                await asyncio.wait_for(fut, min(rem, 1.0) if rem is not None else 1.0)
            except asyncio.TimeoutError:
                if deadline is not None and time.time() >= deadline:
                    return {"state": "timeout"}
            finally:
                lst = self._device_slot_waiters.get(oid)
                if lst is not None:
                    try:
                        lst.remove(fut)
                    except ValueError:
                        pass
                    if not lst:
                        self._device_slot_waiters.pop(oid, None)

    async def _device_fetch_to_head(self, oid: bytes) -> Optional[str]:
        """Materialize a device-tier object into the HEAD's shm store as a
        META_DEVICE envelope (client-mode gets: the remote driver has no
        transfer plane, so the head pulls on its behalf).  Returns None on
        success, else an error string."""
        from ray_tpu._private.serialization import serialize_device_payload
        from ray_tpu.core.device_store import DevicePullError, pull_device_object

        while True:
            rec = self.device_objects.get(oid)
            if not rec or not rec["holders"]:
                return f"ObjectLostError: no live device holder for {oid.hex()[:16]}"
            addr = next(iter(rec["holders"]))
            h = rec["holders"][addr]
            meta = rec["meta"]

            def _pull():
                arr = pull_device_object(addr, h["token"], oid, timeout=300)
                env = serialize_device_payload(
                    memoryview(arr).cast("B"),
                    meta.get("kind", "np"),
                    meta.get("dtype", str(arr.dtype)),
                    meta.get("shape", list(arr.shape)),
                )
                self._store.put_serialized(oid, env)

            try:
                await asyncio.get_running_loop().run_in_executor(None, _pull)
            except DevicePullError as e:
                logger.info(
                    "head-side device pull of %s from %s failed: %s",
                    oid.hex()[:16],
                    addr,
                    e,
                )
                self._device_drop_holder(oid, addr, failed=True)
                continue
            self._add_location(oid, self.head_node_id)
            return None

    def _pin_contained(self, oid: bytes, contained: List[bytes]):
        """Pin the refs pickled inside a stored object for the container's
        lifetime (released in _dec_ref/free when the container is deleted).
        A re-seal with the same ids (eviction refetch) is a no-op; a re-seal
        with different ids (reconstruction re-ran the producer, whose inner
        put ids differ) replaces the old pins with the new ones."""
        inner = [bytes(i) for i in contained]
        prev = self.object_contained.get(oid)
        if prev == inner or (prev is None and not inner):
            return
        if inner:
            self.object_contained[oid] = inner
            for iid in inner:
                self.object_refcounts[iid] = self.object_refcounts.get(iid, 0) + 1
        else:
            self.object_contained.pop(oid, None)
        if prev:
            for iid in prev:
                self._dec_ref(iid)

    def _release_contained(self, oid: bytes):
        for iid in self.object_contained.pop(oid, ()):  # recursive via _dec_ref
            self._dec_ref(iid)

    async def _ensure_object_local(
        self, oid: bytes, dest_nid: bytes, timeout: Optional[float] = None
    ) -> Optional[str]:
        """Make a sealed object present on dest node; returns None on
        success, "__timeout__" if `timeout` lapsed (transfer continues in
        the background), or an error string.  Pulls coalesce per (oid,
        dest) and run as their own task so a timed-out waiter never cancels
        the transfer for other waiters."""
        locs = self.object_locations.get(oid)
        if not locs and oid in self.object_spilled:
            # only durable copy is a spill file: restore it into its node's
            # shm first, then transfer normally
            err = await self._restore_spilled(oid)
            if err is not None:
                return err
            locs = self.object_locations.get(oid)
        if not locs:
            return f"ObjectLostError: {oid.hex()[:16]} sealed but no live copy"
        if dest_nid in locs:
            return None
        key = (oid, dest_nid)
        task = self._pull_inflight.get(key)
        if task is None:

            async def _run():
                try:
                    return await self._pull_to_node(oid, dest_nid)
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "pull of %s to node %s failed: %s",
                        oid.hex()[:16],
                        dest_nid.hex()[:8],
                        e,
                    )
                    return f"transfer failed: {e}"
                finally:
                    self._pull_inflight.pop(key, None)

            task = asyncio.get_running_loop().create_task(_run())
            self._pull_inflight[key] = task
        try:
            return await asyncio.wait_for(asyncio.shield(task), timeout)
        except asyncio.TimeoutError:
            return "__timeout__"

    async def _pull_to_node(self, oid: bytes, dest_nid: bytes) -> Optional[str]:
        """One logical pull = a bounded, backoff-disciplined sequence of
        attempts.  Transfer failures against LIVE sources retry with full
        jitter (a restarting transfer agent or an injected wire fault must
        not immediately escalate to lineage reconstruction); "no live
        copy" is not retried — that is reconstruction's job.  The caller's
        deadline still bounds the whole sequence via _ensure_object_local's
        wait_for."""
        # config counts TOTAL pull rounds; Backoff.max_attempts counts
        # retries (delays granted), hence the -1
        total_rounds = max(1, RayConfig.object_pull_attempts)
        backoff = chaos.Backoff(base=0.1, cap=2.0, max_attempts=total_rounds - 1)
        while True:
            err = await self._pull_to_node_once(oid, dest_nid)
            if err is None or not err.startswith("ObjectLostError"):
                return err
            # a spill may have raced the pull (the holder deleted its shm
            # copy and its SPILL_NOTIFY is in flight): give the notify a
            # beat, then restore-and-retry before declaring the object lost
            await asyncio.sleep(0.3)
            if oid in self.object_spilled:
                rerr = await self._restore_spilled(oid)
                if rerr is None:
                    if dest_nid in self.object_locations.get(oid, ()):
                        return None
                    err2 = await self._pull_to_node_once(oid, dest_nid)
                    if err2 is None:
                        return None
                    err = err2
            if "no live copy" in err:
                return err
            delay = backoff.next_delay()
            if delay is None:
                return err
            logger.info(
                "pull of %s to %s failed (%s); retrying in %.2fs "
                "(round %d/%d)",
                oid.hex()[:16],
                dest_nid.hex()[:8],
                err,
                delay,
                backoff.attempt + 1,
                total_rounds,
            )
            await asyncio.sleep(delay)

    async def _pull_to_node_once(self, oid: bytes, dest_nid: bytes) -> Optional[str]:
        last_err = "no live copy"
        for src_nid in list(self.object_locations.get(oid, ())):
            src = self.nodes.get(src_nid)
            if src is None or not src.alive or not src.transfer_addr:
                continue
            if dest_nid == self.head_node_id:
                try:
                    ok = await asyncio.wait_for(
                        self.object_agent.pull(oid, src.transfer_addr), timeout=300
                    )
                except Exception as e:  # graftlint: disable=silent-except -- captured into last_err, surfaced as the ObjectLostError below
                    ok, last_err = False, f"{type(e).__name__}: {e}"
                if ok:
                    self._add_location(oid, dest_nid)
                    return None
            else:
                dest = self.nodes.get(dest_nid)
                if dest is None or dest.conn is None:
                    return f"destination node {dest_nid.hex()[:8]} gone"
                try:
                    reply = await dest.conn.request(
                        MsgType.OBJECT_PULL,
                        {"object_id": oid, "src_addr": src.transfer_addr},
                        timeout=310,
                    )
                except Exception as e:  # graftlint: disable=silent-except -- captured into last_err via the reply dict, surfaced as ObjectLostError
                    reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                if reply.get("ok"):
                    self._add_location(oid, dest_nid)
                    return None
                last_err = reply.get("error", "pull refused")
        return f"ObjectLostError: transfer of {oid.hex()[:16]} failed: {last_err}"

    async def h_wait_object(self, cid, conn, p):
        if "object_ids" in p:
            return await self._wait_batch(p)
        oid = p["object_id"]
        timeout = p.get("timeout")
        deadline = time.time() + timeout if timeout is not None else None
        dest_nid = bytes(p["node_id"]) if p.get("node_id") is not None else None
        if p.get("device_failed"):
            # the consumer's pull from this holder died: prune it so nobody
            # else is directed at a dead endpoint, then re-resolve below —
            # a surviving holder, the shm envelope, or lineage
            self._device_drop_holder(oid, str(p["device_failed"]), failed=True)
        if p.get("evicted") and dest_nid is not None:
            # client found the object missing from its local store after a
            # sealed reply: that location is stale (LRU-evicted)
            locs = self.object_locations.get(oid)
            if locs is not None:
                locs.discard(dest_nid)
                if not locs:
                    del self.object_locations[oid]
                self._wal_locs(oid)
        while True:
            e = self._object_entry(oid)
            if e[0] == PENDING:
                fut = asyncio.get_running_loop().create_future()
                self.object_waiters.setdefault(oid, []).append(fut)
                rem = None if deadline is None else max(0.001, deadline - time.time())
                try:
                    await asyncio.wait_for(fut, rem)
                except asyncio.TimeoutError:
                    return {"state": "timeout"}
            e = self.objects[oid]
            if e[0] == ERRORED:
                return {"state": "error", "error": e[1]}
            if oid in self.device_objects and dest_nid is not None:
                if p.get("device_ok"):
                    # a pull-capable waiter gets the directive even when it
                    # shares the head's node — the collective plane beats a
                    # head-mediated envelope copy there too
                    directive = await self._device_directive(bytes(oid), deadline)
                    if directive is not None:
                        return directive
                else:
                    # destination can't pull (the head itself in client-mode
                    # gets, or a waiter that predates the device protocol):
                    # materialize a META_DEVICE envelope into the head store
                    # and let the classic host plane below serve it onward
                    derr = await self._device_fetch_to_head(bytes(oid))
                    if derr is None and dest_nid == self.head_node_id:
                        return {"state": "sealed"}
                # holders gone (or envelope now head-local): fall through to
                # the host plane — shm locations, spill restore, or lineage
            if dest_nid is None:
                return {"state": "sealed"}
            # cross-node data plane: fetch the object onto the waiter's node
            # within what's left of the caller's deadline
            rem = None if deadline is None else max(0.001, deadline - time.time())
            err = await self._ensure_object_local(oid, dest_nid, timeout=rem)
            if err is None:
                return {"state": "sealed"}
            if err == "__timeout__":
                return {"state": "timeout"}
            if not err.startswith("ObjectLostError"):
                # dest-side or unexpected transfer error while source copies
                # may be healthy: report it, do NOT wipe valid locations
                return {"state": "error", "error": err}
            # every copy is gone (eviction / node loss): lineage recovery
            # (analog: reference object_recovery_manager.h:90), then loop
            # back to wait for the re-executed task to seal
            self.object_locations.pop(oid, None)
            self._wal_locs(oid)
            rec_err = self._reconstruct_object(oid)
            if rec_err is not None:
                return {"state": "error", "error": err + "; " + rec_err}

    async def _wait_batch(self, p):
        """Server-side ray.wait: block until num_ready of the ids are
        sealed/errored or the timeout passes (analog: reference
        WaitManager, src/ray/raylet/wait_manager.cc).

        Waiter futures register ONCE per pending oid; each round only
        counts completions — re-registering per wake made a 10k-ref wait
        O(N²) in future churn (measured as the 10k-queued drain wall)."""
        oids = [bytes(o) for o in p["object_ids"]]
        want = min(p.get("num_ready", len(oids)), len(oids))
        timeout = p.get("timeout")
        deadline = time.time() + timeout if timeout is not None else None
        n_ready = sum(1 for o in oids if self._object_entry(o)[0] != PENDING)
        registered: List[Tuple[bytes, Any]] = []
        try:
            if n_ready < want and (deadline is None or time.time() < deadline):
                loop = asyncio.get_running_loop()
                # counter + ONE event instead of asyncio.wait over the
                # future set: asyncio.wait re-arms a done-callback on every
                # remaining future per wake — O(N²) churn across a 10k-ref
                # get() (measured ~1.2M future callback ops per 3k drain)
                ev = asyncio.Event()
                state = {"done": 0}

                def _on_done(_f):
                    state["done"] += 1
                    ev.set()

                for o in oids:
                    if self._object_entry(o)[0] == PENDING:
                        f = loop.create_future()
                        f.add_done_callback(_on_done)
                        self.object_waiters.setdefault(o, []).append(f)
                        registered.append((o, f))
                while n_ready + state["done"] < want and state["done"] < len(registered):
                    rem = None if deadline is None else max(0.001, deadline - time.time())
                    if deadline is not None and time.time() >= deadline:
                        break
                    ev.clear()
                    try:
                        await asyncio.wait_for(ev.wait(), rem)
                    except asyncio.TimeoutError:
                        break
            return {"ready": [o for o in oids if self._object_entry(o)[0] != PENDING]}
        finally:
            for o, f in registered:
                if not f.done():
                    f.remove_done_callback(_on_done)
                    f.cancel()
                lst = self.object_waiters.get(o)
                if lst is not None:
                    try:
                        lst.remove(f)
                    except ValueError:
                        pass
                    if not lst:
                        self.object_waiters.pop(o, None)

    def _delete_everywhere(self, oid: bytes):
        """Drop all copies: head store directly, remote nodes by directive
        (including any spill file), and device-store pins by DEVICE_FREE
        push to every holder (fire-and-forget — a holder that misses the
        push only over-pins until its process exits)."""
        rec = self.device_objects.pop(bytes(oid), None)
        if rec:
            self._device_wake(bytes(oid))
            pushed = set()
            for h in rec["holders"].values():
                c = h.get("conn")
                if c is None or id(c) in pushed:
                    continue
                pushed.add(id(c))
                asyncio.get_running_loop().create_task(
                    c.send(MsgType.DEVICE_FREE, {"object_ids": [bytes(oid)]})
                )
        locs = self.object_locations.pop(oid, set())
        self._wal("obj-", bytes(oid))
        for nid in locs:
            if nid == self.head_node_id:
                self._store.delete(oid)
            else:
                node = self.nodes.get(nid)
                if node is not None and node.conn is not None:
                    asyncio.get_running_loop().create_task(
                        node.conn.send(MsgType.OBJECT_DELETE, {"object_ids": [oid]})
                    )
        spilled = self.object_spilled.pop(oid, None)
        if spilled is not None:
            snid, path = spilled
            if snid == self.head_node_id:
                from ray_tpu.raylet.spill import delete_spilled

                delete_spilled(path)
            else:
                node = self.nodes.get(snid)
                if node is not None and node.conn is not None:
                    asyncio.get_running_loop().create_task(
                        node.conn.send(
                            MsgType.OBJECT_DELETE,
                            {"object_ids": [], "spill_paths": [path]},
                        )
                    )
        # even with no recorded location (pre-location legacy puts), try head
        if not locs:
            self._store.delete(oid)

    # --------------------------------------------------------------- spilling

    async def h_client_put(self, cid, conn, p):
        """Remote driver (Ray-Client mode) put: the payload rode the
        control connection; store it in the head node's store and seal
        (reference analog: util/client dataclient put)."""
        from ray_tpu._private.serialization import SerializedObject

        oid = bytes(p["object_id"])
        sobj = SerializedObject.from_wire(p["value"])
        await asyncio.get_running_loop().run_in_executor(
            None, self._store.put_serialized, oid, sobj
        )
        self._pin_contained(oid, p.get("contained") or [])
        self._add_location(oid, self.head_node_id)
        await self._seal_object(oid)
        return {"ok": True}

    async def h_client_get(self, cid, conn, p):
        """Remote driver get: wait for seal, pull the object to the head
        node, return the payload over the control connection."""
        oid = bytes(p["object_id"])
        reply = await self.h_wait_object(
            cid,
            conn,
            {"object_id": oid, "timeout": p.get("timeout"), "node_id": self.head_node_id},
        )
        if reply.get("state") != "sealed":
            return reply
        sobj = await asyncio.get_running_loop().run_in_executor(
            None, self._store.get_serialized, oid
        )
        if sobj is None:
            return {"state": "error", "error": f"ObjectLostError: {oid.hex()[:16]}"}
        return {"state": "sealed", "value": sobj.to_wire()}

    async def h_spill_notify(self, cid, conn, p):
        """A store claimant on `node_id` moved these objects to its disk
        (ray_tpu/raylet/spill.py); record the spill locations and drop the
        now-gone shm locations (reference analog: spilled-URL updates to
        the owner, raylet/local_object_manager.h)."""
        nid = bytes(p["node_id"]) if p.get("node_id") else self.head_node_id
        self._record_spills(nid, {bytes(k): v for k, v in (p.get("spilled") or {}).items()})
        return {"ok": True}

    def _record_spills(self, nid: bytes, spilled: Dict[bytes, str]):
        if spilled:
            self._record_event(
                "INFO", "spill", f"spilled {len(spilled)} objects", node_id=nid.hex()
            )
        for oid, path in spilled.items():
            self.object_spilled[oid] = (nid, path)
            self._wal("spill", bytes(oid), (nid, path))
            locs = self.object_locations.get(oid)
            if locs is not None:
                locs.discard(nid)
                if not locs:
                    del self.object_locations[oid]
            self._wal_locs(oid)

    async def _restore_spilled(self, oid: bytes) -> Optional[str]:
        """Bring a spilled object back into its node's shm store."""
        snid, path = self.object_spilled.get(oid, (None, None))
        if snid is None:
            return f"ObjectLostError: {oid.hex()[:16]} has no spilled copy"
        if snid == self.head_node_id:
            from ray_tpu.raylet.spill import delete_spilled, restore_object

            def _restore_and_clean():
                ok = restore_object(self._store, oid, path)
                if ok:
                    delete_spilled(path)  # back in shm; don't leak the file
                return ok

            ok = await asyncio.get_running_loop().run_in_executor(
                None, _restore_and_clean
            )
        else:
            node = self.nodes.get(snid)
            if node is None or node.conn is None or not node.alive:
                return (
                    f"ObjectLostError: spill node {snid.hex()[:8]} for "
                    f"{oid.hex()[:16]} is gone"
                )
            try:
                reply = await node.conn.request(
                    MsgType.OBJECT_RESTORE,
                    {"object_id": oid, "path": path},
                    timeout=300,
                )
                ok = bool(reply.get("ok"))
            except Exception:  # noqa: BLE001
                logger.warning(
                    "restore RPC for spilled object %s failed",
                    oid.hex()[:16],
                    exc_info=True,
                )
                ok = False
        if not ok:
            return f"ObjectLostError: restore of {oid.hex()[:16]} failed"
        self.object_spilled.pop(oid, None)
        self._wal("spill", bytes(oid), None)
        self._add_location(oid, snid)
        return None

    async def h_free_object(self, cid, conn, p):
        for oid in p["object_ids"]:
            self.objects.pop(oid, None)
            self._obj_mirror.drop(oid)
            self.object_meta.pop(bytes(oid), None)
            self._delete_everywhere(oid)
            self._release_contained(bytes(oid))
        return {"ok": True}

    def _ref_batch_seen(self, p) -> bool:
        """Dedupe re-sent ref flushes (head-FT: a conn loss may race the
        reply, so clients re-send tagged batches after reattach — counter
        bumps are not idempotent on their own)."""
        b = p.get("batch")
        if not b:
            return False
        b = bytes(b)
        if b in self._ref_batches:
            return True
        if len(self._ref_batches_fifo) == self._ref_batches_fifo.maxlen:
            self._ref_batches.discard(self._ref_batches_fifo[0])
        self._ref_batches_fifo.append(b)
        self._ref_batches.add(b)
        return False

    async def h_add_ref(self, cid, conn, p):
        if self._ref_batch_seen(p):
            return {"ok": True, "deduped": True}
        for oid in p["object_ids"]:
            self.object_refcounts[oid] = self.object_refcounts.get(oid, 0) + 1
        return {"ok": True}

    def _pin_args(self, spec: TaskSpec):
        """Bump refcounts of ARG_REF arguments AND refs nested inside
        inlined ARG_VALUE payloads (inverse of _unpin_args)."""
        for aid in self._arg_ref_ids(spec):
            self.object_refcounts[aid] = self.object_refcounts.get(aid, 0) + 1

    def _unpin_args(self, spec: TaskSpec):
        """Release the submit-time pins on ARG_REF + nested arguments
        (paired with the bump in h_submit_task)."""
        for aid in self._arg_ref_ids(spec):
            self._dec_ref(aid)

    @staticmethod
    def _arg_ref_ids(spec: TaskSpec) -> List[bytes]:
        ids = [bytes(arg[2]) for arg in spec.args if arg[0] == 1]  # ARG_REF
        ids.extend(bytes(i) for i in (spec.nested_refs or ()))
        return ids

    def _dec_ref(self, oid: bytes):
        if (
            self._refs_amnesic
            and oid not in self.object_refcounts
            and oid in self.objects
        ):
            # restarted head: this object's pre-crash client refs were
            # never re-announced — the count is UNKNOWN, not zero.  Keep
            # the object (leaks until job teardown) rather than deleting
            # data another peer still references.
            return
        n = self.object_refcounts.get(oid, 0) - 1
        if n <= 0:
            self.object_refcounts.pop(oid, None)
            # out of scope everywhere → evictable; delete eagerly
            self.objects.pop(oid, None)
            self._obj_mirror.drop(oid)
            self.object_meta.pop(oid, None)
            self._delete_everywhere(oid)
            # nobody can ever get() it again → its lineage is dead too
            self._drop_lineage(oid)
            self._reconstructions.pop(oid, None)
            # the deleted container no longer pins the refs inside it
            self._release_contained(oid)
        else:
            self.object_refcounts[oid] = n

    # --------------------------------------------------- lineage / recovery

    def _record_lineage(self, spec: TaskSpec, wire_size: int):
        """Remember the producing spec for each return object, pinning the
        spec's ref-args so reconstruction inputs can't be deleted while the
        lineage is held.  FIFO-evicted beyond lineage_max_bytes; the spec's
        size is charged once per task, not once per return."""
        charged = False
        for oid in spec.return_object_ids():
            if oid in self.lineage:
                charged = True  # already recorded for this task
                continue
            self.lineage[oid] = spec
            self._wal("lineage", bytes(oid), spec.to_wire())
            self._lineage_bytes[oid] = 0 if charged else wire_size
            if not charged:
                self._lineage_total += wire_size
                charged = True
            self._pin_args(spec)
        budget = RayConfig.lineage_max_bytes
        while self._lineage_total > budget and self.lineage:
            evict = next(iter(self.lineage))
            self._drop_lineage(evict)

    def _drop_lineage(self, oid: bytes):
        spec = self.lineage.pop(oid, None)
        if spec is None:
            return
        self._wal("lineage", bytes(oid), None)
        self._lineage_total -= self._lineage_bytes.pop(oid, 0)
        self._unpin_args(spec)

    def _reconstruct_object(self, oid: bytes) -> Optional[str]:
        """Queue re-execution of the producing task for a lost object.
        Returns None if reconstruction is underway, else an error string
        (analog: reference ObjectRecoveryManager::RecoverObject)."""
        spec = self.lineage.get(oid)
        if spec is None:
            return f"ObjectLostError: {oid.hex()[:16]} lost and no lineage retained"
        n = self._reconstructions.get(oid, 0)
        if n >= RayConfig.max_object_reconstructions:
            return (
                f"ObjectLostError: {oid.hex()[:16]} lost after "
                f"{n} reconstruction attempts"
            )
        # every return object of the re-executed task becomes pending again
        for roid in spec.return_object_ids():
            if not self.object_locations.get(roid):
                e = self._object_entry(roid)
                e[0] = PENDING
                e[1] = None
                self._obj_mirror.reset(roid)
        if spec.task_id not in self.tasks:
            # the attempt budget is consumed only by an actual re-execution —
            # concurrent waiters piggyback on the in-flight one for free
            self._reconstructions[oid] = n + 1
            logger.info(
                "reconstructing %s via re-execution of %s",
                oid.hex()[:16],
                spec.function_name,
            )
            # re-pin args exactly like a fresh submit (task_done unpins)
            self._pin_args(spec)
            entry = TaskEntry(spec, -1)
            self.tasks[spec.task_id] = entry
            self.task_queue.append(entry)
            self._kick_scheduler()
        return None

    async def h_remove_ref(self, cid, conn, p):
        if self._ref_batch_seen(p):
            return {"ok": True, "deduped": True}
        for oid in p["object_ids"]:
            self._dec_ref(oid)
        return {"ok": True}

    # ----------------------------------------------------------------- tasks

    async def h_submit_tasks(self, cid, conn, p):
        """Batched submit: a driver-side .remote() burst coalesced into one
        frame (reference analog: the lease-request batching the reference
        gets from per-scheduling-class lease pipelining)."""
        for wire in p["specs"]:
            await self.h_submit_task(cid, conn, {"spec": wire})
        return {"ok": True}

    async def h_submit_task(self, cid, conn, p):
        spec = TaskSpec.from_wire(p["spec"])
        if p.get("resubmit"):
            # post-reattach resubmission of an unacked submit: the task id
            # is the idempotency key — a submit that raced the crash must
            # never double-execute.  During the grace window the verdict
            # can't be final yet (its worker may still be mid-redial), so
            # the spec parks until reconciliation closes.
            if self._recovery is not None:
                self._recovery_resubmits.append((cid, p["spec"]))
                return {"ok": True, "parked": True}
            if self._resubmit_is_duplicate(spec):
                return {"ok": True, "deduped": True}
        # flight recorder: the phases dict is SHARED with p["spec"] (the
        # cached wire reused for PUSH_TASK), so this stamp reaches the
        # worker too.  None when the submitting driver has recording off —
        # that one check is the whole disabled-path cost here.
        if spec.phases is not None:
            spec.phases["head_enqueue"] = time.time()
        for oid in spec.return_object_ids():
            self._object_entry(oid)
        # pin ref-args until the task completes so an eager driver-side
        # del doesn't free an argument out from under the task
        self._pin_args(spec)
        if spec.task_type == ACTOR_TASK:
            return await self._submit_actor_task(spec)
        if spec.task_type == NORMAL_TASK:
            # cheap size estimate for the lineage budget (re-serializing the
            # spec on the submit hot path would double the encode cost)
            est = 256
            for a in spec.args:
                pay = a[2]
                if isinstance(pay, (bytes, bytearray, memoryview)):
                    est += len(pay)  # ARG_REF: object id
                elif isinstance(pay, (list, tuple)) and len(pay) == 3:
                    # ARG_VALUE wire form: [metadata, inband, buffers]
                    est += len(pay[1]) + sum(len(b) for b in pay[2])
                else:
                    est += 64
            self._record_lineage(spec, est)
        entry = TaskEntry(spec, cid, wire=p["spec"])
        self.tasks[spec.task_id] = entry
        self.task_queue.append(entry)
        self._kick_scheduler()
        return {"ok": True}

    async def _submit_actor_task(self, spec: TaskSpec):
        actor = self.actors.get(spec.actor_id)
        if actor is None:
            if self._recovery is not None:
                # the actor's worker may be mid-redial: park the call;
                # _finish_recovery re-runs it once the directory settles
                self._recovery_actor_calls.append(spec)
                return {"ok": True, "parked": True}
            self._unpin_args(spec)
            await self._seal_error_objects(spec, "RayActorError: unknown actor")
            return {"ok": False}
        if actor.state == ACTOR_DEAD:
            self._unpin_args(spec)
            await self._seal_error_objects(
                spec,
                f"RayActorError: {actor.death_cause or 'actor is dead'}"
                f"{actor.death_log_tail}",
            )
            return {"ok": False}
        if (
            actor.state in (ACTOR_PENDING, ACTOR_RESTARTING, ACTOR_PREEMPTED)
            or actor.worker_id is None
        ):
            # PREEMPTED queues too: a call racing the checkpoint/release
            # window must wait for the respawn, not land on a dying worker
            actor.pending_calls.append(spec)
            return {"ok": True, "queued": True}
        await self._push_actor_task(actor, spec)
        return {"ok": True}

    async def _push_actor_task(self, actor: ActorInfo, spec: TaskSpec):
        w = self.workers.get(actor.worker_id)
        if w is None:
            actor.pending_calls.append(spec)
            return
        if spec.phases is not None:
            # actor calls queue in pending_calls while the actor creates /
            # restarts; dispatch is stamped at the actual push so
            # queue_wait covers that wait, like scheduler queueing does
            # for normal tasks
            spec.phases["dispatch"] = time.time()
        entry = TaskEntry(spec, -1)
        entry.state = "RUNNING"
        entry.worker_id = w.worker_id
        entry.node_id = w.node_id
        self.tasks[spec.task_id] = entry
        w.running_tasks.add(spec.task_id)
        await w.conn.send(MsgType.PUSH_TASK, {"spec": spec.to_wire()})

    async def h_task_done(self, cid, conn, p):
        tid = p["task_id"]
        if p.get("replay"):
            # a reattached worker re-sends its recent completions (it
            # can't know which landed before the crash): apply at most once
            if bytes(tid) in self._recent_dones:
                return {"ok": True, "deduped": True}
        self._note_done(tid)
        wid = self._conn_worker.get(cid)
        w = self.workers.get(wid) if wid else None
        if wid is not None and w is None:
            # Zombie report: this worker was already declared dead (its node
            # was removed — SIGKILLed raylets don't reap their workers) and
            # its task has been retried or failed.  Sealing from here would
            # record data on a dead node's store segment; drop it and cut
            # the connection so the orphan exits.
            logger.info("dropping TASK_DONE from de-registered worker %s", wid.hex()[:8])
            conn.close()
            return {"ok": False, "stale": True}
        entry = self.tasks.pop(tid, None)
        if w is not None:
            w.running_tasks.discard(tid)
        self.finished_task_count += 1
        if p.get("exec_end"):
            entry_for_tl = entry  # tid was popped above; there is no fallback
            self.timeline.append(
                {
                    "name": (entry_for_tl.spec.function_name or entry_for_tl.spec.method_name)
                    if entry_for_tl
                    else "task",
                    "pid": w.pid if w else 0,
                    "ts": p.get("exec_start", 0.0),
                    "dur": p["exec_end"] - p.get("exec_start", p["exec_end"]),
                    "error": bool(p.get("error")),
                    # span chain when tracing is on (util/tracing.py)
                    "trace": (entry_for_tl.spec.trace_ctx or {}) if entry_for_tl else {},
                    # flight-recorder stamps → per-phase sub-spans in the
                    # chrome-trace export (h_timeline)
                    "phases": self._join_task_phases(p, entry_for_tl, w),
                    "task_id": bytes(tid).hex(),
                }
            )
        if entry is not None:
            self._unpin_args(entry.spec)
            spec = entry.spec
            node = self.nodes.get(entry.node_id) if entry.node_id else None
            if spec.task_type == NORMAL_TASK:
                if node and not entry.blocked:
                    self._release_task_resources(node, spec)
                if w is not None and not w.dedicated:
                    wnode = self.nodes.get(w.node_id)
                    if wnode is not None:
                        wnode.mark_idle(w)
                    else:
                        w.idle = True
                        w.idle_since = time.time()
            if spec.task_type == ACTOR_CREATION_TASK:
                # default-CPU actors give the creation CPU back once up
                # (or dead): running actors hold 0 CPU by default
                self._release_creation_cpu(self.actors.get(spec.actor_id), node, spec)
            if p.get("error") and spec.task_type == ACTOR_CREATION_TASK:
                actor = self.actors.get(spec.actor_id)
                if actor:
                    await self._destroy_actor(actor, f"creation failed: {p['error']}")
            elif spec.task_type == ACTOR_CREATION_TASK:
                actor = self.actors.get(spec.actor_id)
                if actor:
                    actor.state = ACTOR_ALIVE
                    # a restarted incarnation must not inherit the previous
                    # incarnation's death forensics
                    actor.death_log_tail = ""
                    self._actor_mirror.upsert(actor.actor_id, state=ACTOR_ALIVE)
                    await self._publish("actor", {"actor_id": actor.actor_id, "state": ACTOR_ALIVE})
                    # flush queued calls in order
                    calls, actor.pending_calls = actor.pending_calls, []
                    for call in calls:
                        await self._push_actor_task(actor, call)
        # seal return objects (worker stored them before TASK_DONE).  When the
        # task raised, the worker stores the RayTaskError *as the value* and
        # sets stored_error — the directory seals normally and the client
        # raises on deserialize (reference semantics).
        if p.get("error") and not p.get("stored_error"):
            if entry is not None:
                await self._seal_error_objects(entry.spec, p["error"])
        else:
            seal_nid = w.node_id if w is not None else self._conn_node.get(cid)
            contained = p.get("contained") or {}
            for oid in p.get("sealed", []):
                self._pin_contained(bytes(oid), contained.get(bytes(oid)) or [])
                self._add_location(bytes(oid), seal_nid)
                await self._seal_object(oid)
        self._kick_scheduler()
        return {"ok": True}

    async def h_task_blocked(self, cid, conn, p):
        """Worker blocked in get(): release its cpu so dependents can run
        (analog: reference NotifyDirectCallTaskBlocked → raylet releases the
        lease's cpu, node_manager.cc HandleNotifyDirectCallTaskBlocked)."""
        entry = self.tasks.get(p["task_id"])
        if entry and not entry.blocked and entry.spec.task_type == NORMAL_TASK and entry.node_id:
            node = self.nodes.get(entry.node_id)
            if node:
                entry.blocked = True
                self._release_task_resources(node, entry.spec)
                self._kick_scheduler()
        return {}

    async def h_task_unblocked(self, cid, conn, p):
        entry = self.tasks.get(p["task_id"])
        if entry and entry.blocked and entry.node_id:
            node = self.nodes.get(entry.node_id)
            if node:
                entry.blocked = False
                # reacquire; transient oversubscription is allowed, as in the
                # reference (the worker already holds the lease)
                node.acquire(self._task_resources(entry.spec))
        return {}

    async def h_cancel_task(self, cid, conn, p):
        tid = p["task_id"]
        for e in self.task_queue:
            if e.spec.task_id == tid:
                self.task_queue.remove(e)
                self.tasks.pop(tid, None)
                self._unpin_args(e.spec)
                await self._seal_error_objects(e.spec, "TaskCancelledError: cancelled before execution")
                return {"ok": True, "cancelled": True}
        entry = self.tasks.get(tid)
        if entry is not None and entry.worker_id:
            w = self.workers.get(entry.worker_id)
            if w is not None:
                await w.conn.send(MsgType.CANCEL_TASK, {"task_id": tid})
                if p.get("force"):
                    try:
                        os.kill(w.pid, 9)
                    except OSError:
                        pass
        return {"ok": True, "cancelled": False}

    # ------------------------------- worker leases (control-plane fast path)

    async def h_lease_request(self, cid, conn, p):
        """Grant a worker lease for one resource shape S: the holder pushes
        its whole queue of S-shaped tasks straight to the leased worker's
        direct-call server, amortizing the head round-trip to ~0 per task
        (reference analog: raylet worker-lease reuse,
        node_manager.cc RequestWorkerLease + direct task submission).  The
        lease holds S on the node for its lifetime — per-task accounting
        never touches this loop."""
        if not RayConfig.lease_cache_enabled:
            return {"granted": False, "reason": "disabled"}
        res = {
            str(k): float(v)
            for k, v in (p.get("resources") or {"CPU": 1.0}).items()
        }
        needs_tpu = res.get(RayConfig.tpu_slice_resource_name, 0) > 0
        affinity = p.get("node_id")
        if affinity:
            node = self.nodes.get(bytes(affinity))
            if node is None or not node.alive or not node.try_acquire(res):
                return {"granted": False, "reason": "no capacity"}
        else:
            nid = self.sched.pick_and_acquire(
                res, RayConfig.scheduler_spread_threshold, prefer=self.head_node_id
            )
            if nid is None:
                return {"granted": False, "reason": "no capacity"}
            node = self.nodes.get(nid)
            if node is None:
                return {"granted": False, "reason": "no capacity"}
        worker = node.pop_idle(needs_tpu)
        if worker is None or not worker.direct_addr:
            if worker is not None:
                node.mark_idle(worker)  # registered pre-fast-path: no addr
            node.release(res)
            # denials warm the pool: the client's retry shortly after grants
            self._maybe_spawn_worker(node, 1, needs_tpu)
            return {"granted": False, "reason": "no idle worker"}
        lease_id = os.urandom(12)
        worker.lease = {
            "lease_id": lease_id,
            "cid": cid,
            "resources": res,
            "priority": int(p.get("priority", 1)),
            "via": "head",
            "granted_at": time.time(),
            "revoking": False,
        }
        self.leases[lease_id] = worker.worker_id
        self._leases_by_conn.setdefault(cid, set()).add(lease_id)
        return {
            "granted": True,
            "lease_id": lease_id,
            "worker_id": worker.worker_id,
            "addr": worker.direct_addr,
            "node_id": node.node_id,
        }

    async def h_lease_return(self, cid, conn, p):
        lease_id = bytes(p["lease_id"])
        wid = self.leases.get(lease_id)
        w = self.workers.get(wid) if wid else None
        if w is None or w.lease is None or bytes(w.lease["lease_id"]) != lease_id:
            self.leases.pop(lease_id, None)
            return {"ok": False}
        self._release_lease(w, self.nodes.get(w.node_id), reason="returned")
        self._kick_scheduler()
        return {"ok": True}

    def _release_lease(self, w: WorkerInfo, node: Optional[NodeInfo], reason: str = ""):
        """Idempotent lease teardown: release the shape hold and return
        the worker to the pool (unless it died — the death path forgot it
        already)."""
        lease = w.lease
        if lease is None:
            return
        w.lease = None
        lid = bytes(lease["lease_id"])
        self.leases.pop(lid, None)
        holders = self._leases_by_conn.get(lease.get("cid"))
        if holders is not None:
            holders.discard(lid)
        if node is not None:
            node.release(lease["resources"])
            if (
                w.worker_id in self.workers
                and not w.dedicated
                and w.actor_id is None
            ):
                node.mark_idle(w)

    def _revoke_lease(self, w: WorkerInfo, band: int, reason: str = ""):
        """Lease revocation IS preemption at the grant layer: ask the
        holder to stop pushing and return; a holder that drains within
        ``lease_revoke_deadline_s`` keeps every pushed task's single
        execution (no double-execution), a late one gets its leased worker
        SIGKILLed — the holder then resubmits unreplied tasks on the
        preemption budget (typed PreemptedError once spent)."""
        lease = w.lease
        if lease is None or lease.get("revoking"):
            return
        lease["revoking"] = True
        self._record_preemption(
            "lease",
            victim_band=int(lease.get("priority", 1)),
            requester_band=band,
            name="lease",
            victim=bytes(lease["lease_id"]).hex()[:16],
            reason=reason,
        )
        payload = {"lease_id": lease["lease_id"], "band": band}
        loop = asyncio.get_running_loop()
        if lease.get("via") == "raylet":
            node = self.nodes.get(w.node_id)
            if node is not None and node.conn is not None:
                loop.create_task(
                    node.conn.send(
                        MsgType.PUSH_TASK,
                        {"directive": "revoke_lease", **payload},
                    )
                )
        else:
            conn = self._conns.get(lease.get("cid"))
            if conn is not None:
                loop.create_task(conn.send(MsgType.LEASE_REVOKE, payload))
            else:
                # holder already gone: reclaim directly, nothing to drain
                self._release_lease(w, self.nodes.get(w.node_id), reason="holder gone")
                return
        loop.create_task(self._lease_revoke_deadline(w, lease))

    async def _lease_revoke_deadline(self, w: WorkerInfo, lease: dict):
        await asyncio.sleep(RayConfig.lease_revoke_deadline_s)
        if w.lease is lease:
            # holder didn't drain + return in time: forced preemption —
            # kill the leased worker; its death releases the hold, and the
            # holder's conn loss converts unreplied pushes into
            # budget-accounted preemptions client-side
            self._record_preemption(
                "lease_forced",
                victim_band=int(lease.get("priority", 1)),
                requester_band=-1,
                name="lease",
                victim=bytes(lease["lease_id"]).hex()[:16],
                reason="revoke deadline passed",
            )
            self._kill_worker_process(w, 9)

    async def h_lease_notify(self, cid, conn, p):
        """Async accounting of raylet-local grants (the whole point: the
        head LEARNS about placements instead of brokering them).  Between
        the grant and this frame the node is transiently oversubscribed in
        the head's view — same contract as blocked-task reacquisition."""
        op = str(p.get("op", ""))
        lid = bytes(p.get("lease_id") or b"")
        if op == "grant":
            wid = bytes(p.get("worker_id") or b"")
            w = self.workers.get(wid)
            nid = self._conn_node.get(cid) or (w.node_id if w else None)
            node = self.nodes.get(nid) if nid else None
            res = {
                str(k): float(v) for k, v in (p.get("resources") or {}).items()
            }
            if node is not None:
                node.acquire(res)
            if w is not None:
                if node is not None:
                    node.mark_busy(w)
                w.lease = {
                    "lease_id": lid,
                    "cid": -1,
                    "resources": res,
                    "priority": int(p.get("priority", 1)),
                    "via": "raylet",
                    "granted_at": time.time(),
                    "revoking": False,
                }
                self.leases[lid] = wid
            elif node is not None:
                # unknown worker (raced registration): release to stay sane
                node.release(res)
        elif op == "return":
            wid = self.leases.get(lid)
            w = self.workers.get(wid) if wid else None
            if w is not None and w.lease is not None and bytes(w.lease["lease_id"]) == lid:
                self._release_lease(w, self.nodes.get(w.node_id), reason="raylet return")
            else:
                self.leases.pop(lid, None)
            self._kick_scheduler()
        return {"ok": True}

    async def h_task_stats(self, cid, conn, p):
        """Batched flight records for tasks that never transit the head
        (lease / raylet grants reply straight to the caller): join them
        into the same ring + histograms as TASK_DONE records, tagged with
        granted_by so the queue-wait split is complete."""
        from ray_tpu._private import task_events

        node_hex = bytes(p.get("node_id") or b"").hex()
        for rec in p.get("records", []):
            phases = {
                str(k): float(v) for k, v in (rec.get("phases") or {}).items()
            }
            if not phases:
                continue
            phases.setdefault("done", time.time())
            name = str(rec.get("name") or "task")
            gby = str(rec.get("granted_by") or "cached_lease")
            durs = task_events.durations(phases)
            tid_hex = bytes(rec.get("task_id") or b"").hex()
            self.task_records.append(
                {
                    "task_id": tid_hex,
                    "name": name,
                    "node_id": node_hex,
                    "pid": int(rec.get("pid", 0)),
                    "error": bool(rec.get("error")),
                    "trace": {},
                    "phases": phases,
                    "durations": durs,
                    "granted_by": gby,
                }
            )
            for phase, dur in durs.items():
                self._observe_phase(phase, name, node_hex, dur, granted_by=gby)
            es = phases.get("exec_start")
            if es is not None:
                self.timeline.append(
                    {
                        "name": name,
                        "pid": int(rec.get("pid", 0)),
                        "ts": es,
                        "dur": max(0.0, phases.get("exec_end", es) - es),
                        "error": bool(rec.get("error")),
                        "trace": {},
                        "phases": phases,
                        "task_id": tid_hex,
                    }
                )
        return {}

    # ---------------------------------------------------------------- actors

    async def h_create_actor(self, cid, conn, p):
        spec = TaskSpec.from_wire(p["spec"])
        existing = self.actors.get(spec.actor_id)
        if existing is not None and existing.state != ACTOR_DEAD:
            # idempotent retry: a driver whose CREATE_ACTOR reply was lost
            # to a head crash re-issues it after reattach — the actor id
            # is the dedupe key, creation must not run twice
            existing.owner_conn_id = cid if not existing.detached else existing.owner_conn_id
            return {"ok": True, "existing": True}
        if spec.name:
            key = (spec.namespace, spec.name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing and existing.state != ACTOR_DEAD:
                    raise ValueError(f"actor name {spec.name!r} already taken")
        actor = ActorInfo(spec)
        actor.owner_conn_id = cid
        self.actors[spec.actor_id] = actor
        if spec.name:
            self.named_actors[(spec.namespace, spec.name)] = spec.actor_id
        self._actor_mirror.upsert(
            spec.actor_id,
            state=ACTOR_PENDING,
            name=spec.name,
            namespace=spec.namespace,
            creation_spec=p["spec"],
            direct_addr="",
            death_cause="",
        )
        if spec.detached:
            self._wal("dactor", bytes(spec.actor_id), spec.to_wire())
            self._mark_tables_dirty()
        for oid in spec.return_object_ids():
            self._object_entry(oid)
        # pin creation args like any submit — the creation task's
        # h_task_done unpins (restart re-pins before re-queueing)
        self._pin_args(spec)
        entry = TaskEntry(spec, cid)
        self.tasks[spec.task_id] = entry
        self.task_queue.append(entry)
        self._kick_scheduler()
        return {"ok": True}

    async def h_get_actor(self, cid, conn, p):
        name, namespace = p.get("name", ""), p.get("namespace", "")
        aid = p.get("actor_id") or self.named_actors.get((namespace, name))
        if aid is None or aid not in self.actors:
            return {"found": False}
        a = self.actors[aid]
        return {
            "found": a.state != ACTOR_DEAD,
            "actor_id": a.actor_id,
            "state": a.state,
            "creation_spec": a.creation_spec.to_wire(),
            "direct_addr": a.direct_addr,
        }

    async def h_kill_actor(self, cid, conn, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return {"ok": False}
        if p.get("no_restart", True):
            actor.max_restarts = actor.restarts_used  # forbid further restarts
            await self._destroy_actor(actor, "ray.kill")
        else:
            if actor.worker_id:
                w = self.workers.get(actor.worker_id)
                if w:
                    self._kill_worker_process(w, 9)
        return {"ok": True}

    async def h_actor_state(self, cid, conn, p):
        a = self.actors.get(p["actor_id"])
        if a is None:
            return {"state": "UNKNOWN"}
        if p.get("direct_addr") is not None:
            # the actor's worker registering its direct-call server; the
            # worker's node IP is authoritative for the host part
            host = ""
            w = self.workers.get(a.worker_id) if a.worker_id else None
            node = self.nodes.get(w.node_id) if w else None
            if node is not None and getattr(node, "transfer_addr", None):
                host = str(node.transfer_addr).rsplit(":", 1)[0]
            port = str(p["direct_addr"]).rsplit(":", 1)[-1]
            a.direct_addr = f"{host or '127.0.0.1'}:{port}"
            self._actor_mirror.upsert(a.actor_id, direct_addr=a.direct_addr)
        return {
            "state": a.state,
            "death_cause": a.death_cause,
            "direct_addr": a.direct_addr,
        }

    async def h_list_actors(self, cid, conn, p):
        out = []
        for a in self.actors.values():
            out.append(
                {
                    "actor_id": a.actor_id,
                    "state": a.state,
                    "name": a.name,
                    "namespace": a.namespace,
                    "class_name": a.creation_spec.function_name,
                    "node_id": a.node_id or b"",
                    "worker_id": a.worker_id or b"",
                    "pid": self.workers[a.worker_id].pid if a.worker_id in self.workers else 0,
                }
            )
        return {"actors": out}

    # ------------------------------------------------------ placement groups

    async def h_create_pg(self, cid, conn, p):
        existing = self.pgs.get(bytes(p["pg_id"]))
        if existing is not None and existing.state != "REMOVED":
            # idempotent retry (head-FT parked path): a creator whose reply
            # was lost to a head crash re-issues CREATE_PG after reattach —
            # re-placing would double-reserve the bundles
            return {"ok": True, "placed": existing.state == "CREATED", "existing": True}
        pg = PlacementGroupInfo(p["pg_id"], p["bundles"], p["strategy"], p.get("name", ""))
        self.pgs[pg.pg_id] = pg
        self._wal("pg", bytes(pg.pg_id), (pg.bundles, pg.strategy, pg.name))
        self._mark_tables_dirty()
        self._try_place_pg(pg)
        self._kick_scheduler()
        return {"ok": True, "placed": pg.state == "CREATED"}

    def _try_place_pg(self, pg: PlacementGroupInfo) -> bool:
        """All-or-nothing bundle placement (2-phase reserve in the reference:
        gcs_placement_group_scheduler.cc PrepareResources/CommitResources —
        atomic here because the resource view is centralized)."""
        alive = [n for n in self.nodes.values() if n.alive]
        placement: List[Tuple[int, NodeInfo]] = []
        # simulate against copies of available resources
        sim = {n.node_id: dict(n.resources_available) for n in alive}

        def fits(node, bundle):
            av = sim[node.node_id]
            return all(av.get(k, 0.0) + 1e-9 >= v for k, v in bundle.items() if v > 0)

        def take(node, bundle):
            av = sim[node.node_id]
            for k, v in bundle.items():
                if v > 0:
                    av[k] = av.get(k, 0.0) - v

        strategy = pg.strategy
        if strategy == "STRICT_PACK":
            for n in alive:
                ok = True
                snapshot = dict(sim[n.node_id])
                for b in pg.bundles:
                    if fits(n, b):
                        take(n, b)
                    else:
                        ok = False
                        break
                if not ok:
                    sim[n.node_id] = snapshot
                    continue
                placement = [(i, n) for i in range(len(pg.bundles))]
                break
            if not placement:
                return False
        elif strategy == "STRICT_SPREAD":
            if len(alive) < len(pg.bundles):
                return False
            used_nodes: Set[bytes] = set()
            for i, b in enumerate(pg.bundles):
                cand = [n for n in alive if n.node_id not in used_nodes and fits(n, b)]
                if not cand:
                    return False
                n = max(cand, key=lambda x: x.resources_available.get("CPU", 0))
                take(n, b)
                used_nodes.add(n.node_id)
                placement.append((i, n))
        elif strategy == "SPREAD":
            last = None
            for i, b in enumerate(pg.bundles):
                cand = [n for n in alive if fits(n, b)]
                if not cand:
                    return False
                cand.sort(key=lambda x: (x.node_id == (last or b""), -x.resources_available.get("CPU", 0)))
                n = cand[0]
                take(n, b)
                last = n.node_id
                placement.append((i, n))
        else:  # PACK (default): prefer one node, fall back to others
            for i, b in enumerate(pg.bundles):
                cand = [n for n in alive if fits(n, b)]
                if not cand:
                    return False
                cand.sort(key=lambda x: -x.utilization())
                n = cand[0]
                take(n, b)
                placement.append((i, n))
        # commit
        for i, n in placement:
            n.acquire(pg.bundles[i])
            pg.bundle_nodes[i] = n.node_id
        pg.state = "CREATED"
        pg.bundle_available = [dict(b) for b in pg.bundles]
        for fut in pg.waiters:
            if not fut.done():
                fut.set_result(True)
        pg.waiters.clear()
        return True

    async def h_pg_ready(self, cid, conn, p):
        pg = self.pgs.get(p["pg_id"])
        if pg is None:
            raise ValueError("unknown placement group")
        if pg.state == "CREATED":
            return {"ready": True}
        fut = asyncio.get_running_loop().create_future()
        pg.waiters.append(fut)
        try:
            await asyncio.wait_for(fut, p.get("timeout"))
            return {"ready": True}
        except asyncio.TimeoutError:
            return {"ready": False}

    async def h_remove_pg(self, cid, conn, p):
        self._wal("pg", bytes(p["pg_id"]), None)
        self._mark_tables_dirty()
        pg = self.pgs.pop(p["pg_id"], None)
        if pg is None:
            return {"ok": False}
        if pg.state == "CREATED":
            for i, nid in enumerate(pg.bundle_nodes):
                node = self.nodes.get(nid) if nid else None
                if node:
                    # release what the PG still holds (reserved minus consumed is
                    # held by running tasks; they release into the node on finish)
                    node.release(pg.bundle_available[i])
        pg.state = "REMOVED"
        return {"ok": True}

    async def h_get_pg(self, cid, conn, p):
        pg = self.pgs.get(p["pg_id"])
        if pg is None:
            return {"found": False}
        return {
            "found": True,
            "state": pg.state,
            "bundles": pg.bundles,
            "strategy": pg.strategy,
            "bundle_nodes": [n or b"" for n in pg.bundle_nodes],
        }

    async def h_list_pgs(self, cid, conn, p):
        return {
            "pgs": [
                {"pg_id": pg.pg_id, "name": pg.name, "state": pg.state, "strategy": pg.strategy}
                for pg in self.pgs.values()
            ]
        }

    # ------------------------------------------------------------- KV/pubsub

    async def h_kv_put(self, cid, conn, p):
        self._mark_tables_dirty()
        key = p["key"]
        # shared put path with the shard servers (gcs/shards.py): store +
        # wake kv waiters wherever they registered (head or shard loops).
        # No kv:{key} pubsub publish: nothing subscribes to it, and with
        # clients routing KV_PUT to the shard listeners a head-only
        # publish would be a silent divergence trap anyway — waiters are
        # the notification mechanism for kv rendezvous.
        added = self.kv.put_notify(key, p["value"], p.get("overwrite", True))
        if added:
            self._wal("kv", key, p["value"])
        return {"added": added}

    async def h_kv_get(self, cid, conn, p):
        key = p["key"]
        if p.get("wait") and key not in self.kv:
            # waiter future fired by put_notify — not a poll loop: N
            # rendezvousing workers cost zero wakeups until the key lands
            timeout = p.get("timeout") or RayConfig.collective_rendezvous_timeout_s
            fut = self.kv.register_waiter(key)
            if fut is not None:
                try:
                    await asyncio.wait_for(fut, timeout)
                except asyncio.TimeoutError:
                    return {"found": False}
                finally:
                    self.kv.unregister_waiter(key, fut)
        v = self.kv.get(key)
        return {"found": v is not None, "value": v if v is not None else b""}

    async def h_kv_del(self, cid, conn, p):
        self._mark_tables_dirty()
        n = 0
        if p.get("prefix"):
            for k in [k for k in self.kv if k.startswith(p["key"])]:
                del self.kv[k]
                self._wal("kv", k, None)
                n += 1
        elif p["key"] in self.kv:
            del self.kv[p["key"]]
            self._wal("kv", p["key"], None)
            n = 1
        return {"deleted": n}

    async def h_kv_keys(self, cid, conn, p):
        pref = p.get("prefix", "")
        keys = [k for k in self.kv if k.startswith(pref)]
        if p.get("values"):
            # prefix-ranged multi-get: one frame instead of 1+N round
            # trips (the raylet metrics agents scrape the metrics:*
            # namespace this way every Prometheus interval)
            return {"keys": keys, "values": {k: self.kv[k] for k in keys}}
        return {"keys": keys}

    async def h_kv_exists(self, cid, conn, p):
        return {"exists": p["key"] in self.kv}

    async def h_subscribe(self, cid, conn, p):
        self.subscribers.setdefault(p["channel"], {})[cid] = conn
        return {"ok": True}

    async def h_publish(self, cid, conn, p):
        await self._publish(p["channel"], p["message"])
        return {"ok": True}

    async def _publish(self, channel: str, message: dict):
        if channel == "logs":
            self._account_log_message(message)
            if str(message.get("source", "")).startswith("driver-"):
                # driver-tee files are for LOG_FETCH retrieval only: the
                # driver already printed these bytes to its own terminal,
                # and streaming them back would echo through the tee →
                # tailer → sink → tee loop, amplifying every line
                return
        subs = self.subscribers.get(channel)
        if not subs:
            return
        dead = []
        # snapshot: the awaits inside the loop yield to handlers that
        # subscribe/unsubscribe, which would mutate the dict mid-iteration
        # (observed as a RuntimeError storm during mass worker death)
        for cid, conn in list(subs.items()):
            msg = message
            if channel == "logs":
                msg = self._scope_log_message(cid, message)
                if msg is None:
                    continue  # nothing in this batch belongs to that driver
            try:
                await conn.send(MsgType.PUBLISH, {"channel": channel, "message": msg})
            except Exception:  # graftlint: disable=silent-except -- dead subscriber is expected churn; pruned from the channel just below
                dead.append(cid)
        for cid in dead:
            subs.pop(cid, None)

    def _account_log_message(self, message: dict):
        """Head-side transit accounting for one tailer batch: line
        counters by stream/node, the per-source forensics ring (feeds
        ActorDiedError.log_tail — a SIGKILLed victim can't ship its own
        tail), and trace-stamped records for the timeline markers."""
        records = message.get("records")
        if not records:
            return
        source = message.get("source", "")
        from collections import deque as _deque

        ring = self._recent_logs.get(source)
        if ring is None:
            ring = self._recent_logs[source] = _deque(
                maxlen=max(8, RayConfig.error_log_tail_lines)
            )
            if len(self._recent_logs) > 4096:
                # bound source cardinality across very long sessions
                self._recent_logs.pop(next(iter(self._recent_logs)))
        by_stream: Dict[str, Dict[str, int]] = {}
        for rec in records:
            ring.append(rec.get("msg", ""))
            stream = rec.get("stream", "out")
            node = str(rec.get("node") or "head")
            per = by_stream.setdefault(stream, {})
            per[node] = per.get(node, 0) + 1
            if rec.get("trace"):
                self._log_trace_marks.append(rec)
        for stream, per in by_stream.items():
            for node, n in per.items():
                self._inc_counter(
                    "ray_tpu_log_lines_total",
                    "log lines transiting the head's logs channel, by stream/node",
                    {"stream": stream, "node": node},
                    float(n),
                )

    def _scope_log_message(self, cid: int, message: dict) -> Optional[dict]:
        """Job-scope one tailer batch for one subscriber: a driver conn
        sees records stamped with ITS job plus stamp-free lines (raw mode,
        infra output); non-driver subscribers see everything.  Returns
        None when the filtered batch is empty."""
        job = self._conn_job.get(cid)
        if job is None:
            return message  # not a registered driver: unscoped (tests, tools)
        records = message.get("records")
        if records is None:
            return message  # v1 raw batch (structured capture off): unscoped
        job_hex = bytes(job).hex()
        kept = [
            r for r in records if not r.get("job") or r.get("job") == job_hex
        ]
        if not kept:
            return None
        if len(kept) == len(records):
            return message
        return {
            "source": message.get("source"),
            "lines": [r.get("msg", "") for r in kept],
            "records": kept,
        }

    def _with_log_tail(self, worker_id: Optional[bytes]) -> str:
        """LOG_TAIL_MARKER suffix for a seal string: the victim worker's
        last lines as seen by the logs pubsub transit.  The dead process
        cannot ship its own forensics — this ring is the survivor copy.
        Empty string when capture is off or nothing transited yet."""
        if not worker_id or not _log_plane.enabled:
            # RAY_TPU_LOG_STRUCTURED=0 contract: no sentinel-marked tail
            # may enter a seal string — a worker printing the resulting
            # exception would leak stamp bytes into a raw-mode log file
            return ""
        info = self._worker_log_src.get(bytes(worker_id))
        ring = self._recent_logs.get(info["src"]) if info else None
        if not ring:
            return ""
        import json as _json

        try:
            return _log_plane.LOG_TAIL_MARKER + _json.dumps(list(ring))
        except (TypeError, ValueError):
            return ""

    def _note_error_record(self, p: dict):
        """One structured error record into the head ring + signature
        dedup index + counter family — shared by ERROR_REPORT frames and
        head-side actor-death synthesis so `summary errors` sees both."""
        sig = str(p.get("signature") or "unknown")
        kind = str(p.get("kind") or "task")
        rec = dict(p)
        rec["ts"] = time.time()
        self.error_records.append(rec)
        ent = self._error_index.get(sig)
        if ent is None:
            if len(self._error_index) >= 1024:
                # bound distinct-signature cardinality; oldest group goes
                self._error_index.pop(next(iter(self._error_index)))
            self._error_index[sig] = {
                "signature": sig,
                "kind": kind,
                "first_ts": rec["ts"],
                "last_ts": rec["ts"],
                "count": 1,
                "sample": rec,
            }
            # first sighting of a NEW signature is event-worthy; repeats
            # only bump the dedup count (flood-safe by construction)
            self._record_event(
                "ERROR",
                "errors",
                f"{rec.get('exc_type', 'Error')} in {rec.get('name', '?')}: "
                f"{str(rec.get('message', ''))[:200]}",
                signature=sig,
                kind=kind,
            )
        else:
            ent["count"] += 1
            ent["last_ts"] = rec["ts"]
            ent["sample"] = rec
        self._inc_counter(
            "ray_tpu_error_records_total",
            "structured error records received on the head error ring, by kind",
            {"kind": kind},
            1.0,
        )

    async def h_error_report(self, cid, conn, p):
        """Resurrected ERROR_PUSH role (new burned-in value): a worker's
        uncaught task/actor exception arrives as a structured record —
        signature, traceback, last-K log lines — fire-and-forget (rid 0,
        no reply)."""
        self._note_error_record(p)
        return {"ok": True}

    # ------------------------------------------------- log plane: retrieval

    def _resolve_log_entity(self, kind: str, ident: str):
        """Entity → files on nodes.  Returns
        ``(targets: {node_id: [paths]}, rec_filter: (key, hexprefix)|None,
        job_hex|None)``; raises ValueError with a user-facing message when
        the entity doesn't resolve."""
        targets: Dict[bytes, List[str]] = {}
        rec_filter = None
        job_hex = None

        def _add_worker(wid: bytes):
            info = self._worker_log_src.get(bytes(wid))
            if not info:
                w = self.workers.get(bytes(wid))
                if w is None or not w.log_file:
                    raise ValueError(
                        f"no log file known for worker {bytes(wid).hex()[:8]}"
                    )
                info = {"node": w.node_id, "path": w.log_file}
            targets.setdefault(bytes(info["node"]), []).append(info["path"])

        def _actor_worker(aid_hex: str) -> bytes:
            for aid, actor in self.actors.items():
                if aid.hex().startswith(aid_hex):
                    if actor.worker_id is None:
                        raise ValueError(
                            f"actor {aid_hex[:8]} has no worker (state "
                            f"{actor.state}): no log file to read"
                        )
                    return bytes(actor.worker_id)
            raise ValueError(f"unknown actor {aid_hex[:8]}")

        if kind == "worker":
            for wid in list(self._worker_log_src) + list(self.workers):
                if wid.hex().startswith(ident):
                    _add_worker(wid)
                    break
            else:
                raise ValueError(f"unknown worker {ident[:8]}")
        elif kind == "actor":
            wid = _actor_worker(ident)
            _add_worker(wid)
            rec_filter = ("actor", ident)
        elif kind == "replica":
            # "deployment#index": replicas are named actors
            # SERVE_REPLICA::{deployment}::{gen}::{rseq} (serve/controller.py)
            dep, _, idx = ident.partition("#")
            idx = int(idx or 0)
            prefix = f"SERVE_REPLICA::{dep}::"
            names = sorted(
                (name, aid)
                for (_ns, name), aid in self.named_actors.items()
                if name.startswith(prefix)
            )
            if not names:
                raise ValueError(f"no live replicas for deployment {dep!r}")
            if idx >= len(names):
                raise ValueError(
                    f"replica index {idx} out of range: deployment {dep!r} "
                    f"has {len(names)} live replica(s)"
                )
            aid = names[idx][1]
            wid = _actor_worker(bytes(aid).hex())
            _add_worker(wid)
            rec_filter = ("actor", bytes(aid).hex())
        elif kind == "task":
            # the running-task stamp addresses lines; read the whole
            # cluster's files filtered down to this task's records
            for info in self._worker_log_src.values():
                targets.setdefault(bytes(info["node"]), []).append(info["path"])
            rec_filter = ("task", ident)
        elif kind == "job":
            job_hex = ident
            for info in self._worker_log_src.values():
                targets.setdefault(bytes(info["node"]), []).append(info["path"])
            # the driver tee lands on the head node as driver-{job8}-*.log
            import glob as _glob

            for path in _glob.glob(
                os.path.join(self.session_dir, f"driver-{ident[:8]}*.log")
            ):
                targets.setdefault(self.head_node_id, []).append(path)
        elif kind == "node":
            for nid in self.nodes:
                if nid.hex().startswith(ident):
                    break
            else:
                raise ValueError(f"unknown node {ident[:8]}")
            for info in self._worker_log_src.values():
                if bytes(info["node"]) == nid:
                    targets.setdefault(nid, []).append(info["path"])
            if nid == self.head_node_id:
                head_log = os.path.join(self.session_dir, "head.log")
                if os.path.exists(head_log):
                    targets.setdefault(nid, []).append(head_log)
            if not targets:
                raise ValueError(
                    f"node {ident[:8]} has no registered worker logs yet"
                )
        else:
            raise ValueError(f"unknown log entity kind {kind!r}")
        return targets, rec_filter, job_hex

    def _fetch_log_local(self, payload: dict) -> dict:
        """The head is its own node's log agent (no raylet on the head):
        same read the raylet-side agent performs, same session-dir jail."""
        from ray_tpu._private import log_monitor

        sess = os.path.realpath(self.session_dir)
        files = [
            f
            for f in (payload.get("files") or [])
            if os.path.realpath(f).startswith(sess + os.sep)
        ]
        cursor = payload.get("cursor") or None
        grep = payload.get("grep") or None
        job = payload.get("job") or None
        if cursor:
            recs, cur = log_monitor.read_new_records(cursor, grep=grep, job=job)
        else:
            recs, cur = log_monitor.tail_file_records(
                files, tail=int(payload.get("tail") or 100), grep=grep, job=job
            )
        return {"ok": True, "records": recs, "cursor": cur}

    async def _fetch_log_from(self, nid: bytes, payload: dict) -> dict:
        if nid == self.head_node_id:
            return await asyncio.get_running_loop().run_in_executor(
                None, self._fetch_log_local, payload
            )
        node = self.nodes.get(nid)
        if node is None or node.conn is None or not node.alive:
            return {
                "ok": False,
                "error": f"node {nid.hex()[:8]} is not reachable",
            }
        return await node.conn.request(MsgType.LOG_FETCH, payload, timeout=30)

    async def h_log_fetch(self, cid, conn, p):
        """Pull-based log retrieval: resolve the entity to files on nodes,
        delegate the disk read to each node's log agent, merge by
        timestamp.  ``cursor`` (from a prior reply) switches to a follow
        read — only new complete lines since that reply."""
        kind = str(p.get("kind") or "worker")
        ident = str(p.get("id") or "")
        tail = int(p.get("tail") or 100)
        grep = p.get("grep") or None
        cursor = p.get("cursor") or None

        if kind == "list":
            # directory view (state API list_logs): every log file the
            # head can currently resolve, as node:basename strings
            files = sorted(
                {
                    f"{bytes(info['node']).hex()[:12]}:{info['src']}"
                    for info in self._worker_log_src.values()
                    if not ident or bytes(info["node"]).hex().startswith(ident)
                }
            )
            return {"ok": True, "files": files}

        if cursor:
            # follow: the reply cursor is {node_hex: {path: offset}} — route
            # each sub-cursor back to the node that owns those files
            jobs = [
                (nh, {"cursor": sub, "grep": grep, "job": p.get("job") or None})
                for nh, sub in cursor.items()
                if sub
            ]
            records: List[dict] = []
            out_cursor: Dict[str, dict] = {}
            for nh, payload in jobs:
                r = await self._fetch_log_from(bytes.fromhex(nh), payload)
                if not r.get("ok"):
                    return r
                records.extend(r.get("records") or [])
                out_cursor[nh] = r.get("cursor") or {}
            records.sort(key=lambda r: r.get("ts") or 0.0)
            return {"ok": True, "records": records, "cursor": out_cursor}

        try:
            targets, rec_filter, job_hex = self._resolve_log_entity(kind, ident)
        except ValueError as e:
            return {"ok": False, "error": str(e)}
        if p.get("job") and not job_hex:
            job_hex = str(p["job"])
        records = []
        out_cursor = {}
        for nid, files in targets.items():
            r = await self._fetch_log_from(
                nid,
                {"files": files, "tail": tail, "grep": grep, "job": job_hex},
            )
            if not r.get("ok"):
                # partial reach (a node died mid-query) degrades, not fails,
                # a multi-node read; a single-target read surfaces the error
                if len(targets) == 1:
                    return r
                continue
            records.extend(r.get("records") or [])
            out_cursor[nid.hex()] = r.get("cursor") or {}
        if rec_filter is not None:
            key, prefix = rec_filter
            records = [
                r for r in records if str(r.get(key, "")).startswith(prefix)
            ]
        records.sort(key=lambda r: r.get("ts") or 0.0)
        if tail > 0:
            records = records[-tail:]
        return {"ok": True, "records": records, "cursor": out_cursor}

    # -------------------------------------------------------- cluster state

    async def h_cluster_resources(self, cid, conn, p):
        total: Dict[str, float] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.resources_total.items():
                    total[k] = total.get(k, 0.0) + v
        return {"resources": total}

    async def h_available_resources(self, cid, conn, p):
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.resources_available.items():
                    avail[k] = avail.get(k, 0.0) + v
        return {"resources": avail}

    async def h_list_nodes(self, cid, conn, p):
        return {
            "nodes": [
                {
                    "node_id": n.node_id,
                    "alive": n.alive,
                    "resources": n.resources_total,
                    "available": n.resources_available,
                    "labels": n.labels,
                    "num_workers": len(n.workers),
                    "idle_workers": len(n.idle_pool[False]) + len(n.idle_pool[True]),
                    "starting_workers": n.starting_workers,
                }
                for n in self.nodes.values()
            ]
        }

    async def h_list_tasks(self, cid, conn, p):
        out = []
        for e in self.task_queue:
            out.append(
                {
                    "task_id": e.spec.task_id,
                    "state": "QUEUED",
                    "name": e.spec.function_name,
                    "resources": self._task_resources(e.spec),
                }
            )
        for e in self.tasks.values():
            if e.state != "QUEUED":
                out.append(
                    {
                        "task_id": e.spec.task_id,
                        "state": e.state,
                        "name": e.spec.function_name,
                        "type": e.spec.task_type,
                        "worker_id": e.worker_id or b"",
                    }
                )
        return {"tasks": out, "finished": self.finished_task_count}

    # -------------------------------------------------------- flight recorder

    def _join_task_phases(self, p: dict, entry, w) -> dict:
        """Join the TASK_DONE stamps with head-side context into one flight
        record, aggregate per-phase histograms, and return the stamp dict
        for the timeline event.  One truthiness check when recording is off
        (the worker sends phases={} then)."""
        wire_phases = p.get("phases")
        if not wire_phases:
            return {}
        from ray_tpu._private import task_events

        phases = {str(k): float(v) for k, v in wire_phases.items()}
        phases["done"] = time.time()
        spec = entry.spec if entry is not None else None
        name = (spec.function_name or spec.method_name) if spec else "task"
        gby = str(getattr(spec, "granted_by", "head") or "head") if spec else "head"
        node_hex = (entry.node_id.hex() if entry and entry.node_id else "")
        durs = task_events.durations(phases)
        self.task_records.append(
            {
                "task_id": bytes(p["task_id"]).hex(),
                "name": name or "task",
                "node_id": node_hex,
                "pid": w.pid if w else 0,
                "error": bool(p.get("error")),
                "trace": (spec.trace_ctx or {}) if spec else {},
                "phases": phases,
                "durations": durs,
                "granted_by": gby,
            }
        )
        for phase, dur in durs.items():
            self._observe_phase(phase, name or "task", node_hex, dur, granted_by=gby)
        return phases

    def _observe_phase(
        self,
        phase: str,
        name: str,
        node_hex: str,
        dur: float,
        granted_by: str = "",
    ):
        """Fold one task-phase duration into the flight-recorder
        histograms (see _observe_hist for the write-through contract).
        Task records carry the grant path (head / cached_lease / raylet)
        as a label so queue-wait splits by dispatch mode; the dag/serve/
        train planes omit it."""
        from ray_tpu._private import task_events

        tags = {"phase": phase, "name": name, "node": node_hex[:12]}
        if granted_by:
            tags["granted_by"] = granted_by
        self._observe_hist(
            task_events.PHASE_METRIC,
            task_events.PHASE_METRIC_HELP,
            task_events.PHASE_HISTOGRAM_BOUNDARIES,
            tags,
            dur,
        )

    def _observe_hist(self, metric, help_text, boundaries, tags, value):
        """Fold one observation into a head-owned histogram series,
        written through to self.kv under metrics:* so the normal scrape
        surfaces (util/metrics.read_all, per-node /metrics) pick it up
        like any app metric.  Deliberately NOT WAL-persisted (direct kv
        mutation, like chaos:plan): latency history dies with the head
        incarnation."""
        import json as _json

        from ray_tpu.util import metrics as metrics_mod

        key = f"metrics:{metric}:{metrics_mod.tag_string(tags)}:head"
        rec = self._phase_hist_cache.get(key)
        if rec is None:
            rec = metrics_mod.new_histogram_record(help_text, boundaries)
            rec["tags"] = tags
            self._phase_hist_cache[key] = rec
        metrics_mod.observe_into(rec, value)
        self.kv[key] = _json.dumps(rec).encode()

    def _set_gauge(self, metric, help_text, tags, value):
        """Head-owned gauge series, same write-through as _observe_hist."""
        import json as _json

        from ray_tpu.util import metrics as metrics_mod

        key = f"metrics:{metric}:{metrics_mod.tag_string(tags)}:head"
        rec = {
            "kind": "gauge",
            "value": float(value),
            "ts": time.time(),
            "description": help_text,
            "tags": tags,
        }
        self.kv[key] = _json.dumps(rec).encode()

    async def h_task_summary(self, cid, conn, p):
        """Workload summaries over the joined flight records.  `what`
        selects the plane: "tasks" (default — per-phase latency table,
        the backend of `ray-tpu summary tasks` / /api/task_summary),
        "serve" (per-deployment stage latencies + TTFT/TPOT), "train"
        (per-run step breakdown + jitter/MFU), "memory" (per-node store
        occupancy, object accounting, DAG ring occupancy, spill
        counters), "slo" (the watchdog's verdicts), "preemptions" (the
        priority scheduler's victim log, counters, parked actors and
        SLO hold).  Reference analog: `ray summary tasks`,
        state/state_cli.py."""
        what = str(p.get("what", "tasks"))
        limit = int(p.get("limit", 0))
        if what == "serve":
            return self._summary_serve(limit)
        if what == "train":
            return self._summary_train(limit)
        if what == "memory":
            return self._summary_memory()
        if what == "slo":
            return self._summary_slo()
        if what == "preemptions":
            return self._summary_preemptions(limit)
        if what == "errors":
            return self._summary_errors(limit)
        if what == "head":
            return {
                "incarnation": self.incarnation,
                "head_node_id": self.head_node_id.hex(),
                "started_at": self.started_at,
                "restarts_total": self.incarnation - 1,
                "recovering": self._recovery is not None,
                "last_recovery": self.last_recovery,
            }
        if what != "tasks":
            raise ValueError(f"unknown summary kind {what!r}")
        records = list(self.task_records)
        groups: Dict[Tuple[str, str], List[float]] = {}
        for rec in records:
            for phase, dur in rec["durations"].items():
                groups.setdefault((rec["name"], phase), []).append(dur)
        summary = []
        for (name, phase), vals in sorted(groups.items()):
            summary.append({"name": name, "phase": phase, **_percentiles(vals)})
        out = {"summary": summary, "total_records": len(records)}
        if limit > 0:
            out["records"] = records[-limit:]
        return out

    def _summary_serve(self, limit: int = 0) -> dict:
        """Per-(deployment, stage) latency table plus TTFT/TPOT
        percentiles, aggregated over the serve flight records."""
        records = [
            r for r in self.task_records if r["name"].startswith("serve:")
        ]
        stages: Dict[Tuple[str, str], List[float]] = {}
        ttft: Dict[str, List[float]] = {}
        tpot: Dict[str, List[float]] = {}
        for rec in records:
            dep = rec["name"][len("serve:"):]
            for phase, dur in rec["durations"].items():
                stages.setdefault((dep, phase), []).append(dur)
            if rec.get("ttft_s") is not None:
                ttft.setdefault(dep, []).append(float(rec["ttft_s"]))
            if rec.get("tpot_s") is not None:
                tpot.setdefault(dep, []).append(float(rec["tpot_s"]))
        summary = [
            {"deployment": dep, "stage": stage, **_percentiles(vals)}
            for (dep, stage), vals in sorted(stages.items())
        ]
        out = {
            "summary": summary,
            "ttft": {d: _percentiles(v) for d, v in ttft.items()},
            "tpot": {d: _percentiles(v) for d, v in tpot.items()},
            "engine": self._engine_gauges(),
            "fleet": self._fleet_gauges(),
            "total_records": len(records),
        }
        if limit > 0:
            out["records"] = records[-limit:]
        return out

    def _fleet_gauges(self) -> dict:
        """Fleet-survival view per deployment, read from the
        ``ray_tpu_serve_fleet_*`` families (controller publishes
        replicas/scale/drain, handles publish failovers; counter series
        sum across processes) — `ray-tpu summary serve`'s fleet block."""
        from ray_tpu.util import metrics as metrics_mod

        raw = metrics_mod.raw_records_from_kv(self.kv)
        fleet_raw = {
            k: v for k, v in raw.items() if k.startswith("ray_tpu_serve_fleet_")
        }
        if not fleet_raw:
            return {}
        out: dict = {}
        for key, rec in sorted(metrics_mod.merge_series(fleet_raw).items()):
            name, _, _ = metrics_mod.parse_series_key(key)
            tags = dict(rec.get("tags") or {})
            dep = tags.pop("deployment", "?")
            slot = out.setdefault(dep, {})
            short = name[len("ray_tpu_serve_fleet_"):]
            if tags:
                short += ":" + ",".join(f"{v}" for _, v in sorted(tags.items()))
            slot[short] = rec.get("value", 0.0)
        return out

    def _engine_gauges(self) -> dict:
        """Continuous-batching engine occupancy, read from the replica-
        published ``ray_tpu_serve_engine_*`` gauge families in the metrics
        kv namespace (per-process series merged, freshest write wins) —
        slot/page occupancy and queue depth per deployment for
        `ray-tpu summary serve|memory`."""
        from ray_tpu.util import metrics as metrics_mod

        raw = metrics_mod.raw_records_from_kv(self.kv)
        engine_raw = {
            k: v for k, v in raw.items() if k.startswith("ray_tpu_serve_engine_")
        }
        if not engine_raw:
            return {}
        out: dict = {}
        for key, rec in sorted(metrics_mod.merge_series(engine_raw).items()):
            name, _, _ = metrics_mod.parse_series_key(key)
            tags = dict(rec.get("tags") or {})
            dep = tags.pop("deployment", "?")
            slot = out.setdefault(dep, {})
            short = name[len("ray_tpu_serve_engine_"):]
            if tags:
                short += ":" + ",".join(f"{v}" for _, v in sorted(tags.items()))
            slot[short] = rec.get("value", 0.0)
        return out

    def _summary_train(self, limit: int = 0) -> dict:
        """Per-run step breakdown (phase percentiles over the record
        ring) plus the freshest rolling stats each probe shipped
        (jitter/MFU over ITS window, which outlives the ring)."""
        records = [
            r for r in self.task_records if r["name"].startswith("train:")
        ]
        groups: Dict[Tuple[str, str], List[float]] = {}
        for rec in records:
            run = rec["name"][len("train:"):]
            for phase, dur in rec["durations"].items():
                groups.setdefault((run, phase), []).append(dur)
        summary = [
            {"run": run, "phase": phase, **_percentiles(vals)}
            for (run, phase), vals in sorted(groups.items())
        ]
        out = {
            "summary": summary,
            "runs": {k: dict(v) for k, v in self.train_stats.items()},
            "total_records": len(records),
        }
        if limit > 0:
            out["records"] = records[-limit:]
        return out

    def _summary_memory(self) -> dict:
        """Cluster memory accounting: per-node shm occupancy, the object
        directory by state/owner, spill counters, DAG ring occupancy."""
        nodes = {}
        for nid, node in self.nodes.items():
            stats = dict(node.store_stats)
            if nid == self.head_node_id and getattr(self, "_store", None):
                stats = {
                    "used": float(self._store.used()),
                    "capacity": float(self._store.capacity()),
                    "objects": float(self._store.num_objects()),
                    "evictions": float(self._store.evictions()),
                }
            nodes[nid.hex()] = {"alive": node.alive, **stats}
        by_state: Dict[str, int] = {"SEALED": 0, "PENDING": 0, "ERRORED": 0}
        for entry in self.objects.values():
            key = {PENDING: "PENDING", SEALED: "SEALED", ERRORED: "ERRORED"}[entry[0]]
            by_state[key] += 1
        by_owner: Dict[str, dict] = {}
        by_tier: Dict[str, dict] = {}
        for oid, meta in self.object_meta.items():
            if oid not in self.objects:
                continue
            slot = by_owner.setdefault(
                meta.get("owner", "?"), {"count": 0, "bytes": 0}
            )
            slot["count"] += 1
            slot["bytes"] += int(meta.get("nbytes", 0))
            # tier accounting: a device object that spilled was re-sealed
            # with tier="shm", so it lands in exactly one bucket here
            tslot = by_tier.setdefault(
                meta.get("tier", "shm"), {"count": 0, "bytes": 0}
            )
            tslot["count"] += 1
            tslot["bytes"] += int(meta.get("nbytes", 0))
        pinned = sum(1 for c in self.object_refcounts.values() if c > 0)
        device_holders = sum(
            len(r["holders"]) for r in self.device_objects.values()
        )
        return {
            "nodes": nodes,
            "objects": {
                "by_state": by_state,
                "by_owner": by_owner,
                "by_tier": by_tier,
                "pinned": pinned,
                "total": len(self.objects),
                "spilled": len(self.object_spilled),
                "lineage": len(self.lineage),
            },
            "device_tier": {
                "objects": len(self.device_objects),
                "bytes": sum(
                    int(r["meta"].get("nbytes", 0))
                    for r in self.device_objects.values()
                ),
                "holders": device_holders,
            },
            "dag_channels": {k: dict(v) for k, v in self.dag_channel_stats.items()},
            # per-deployment paged-KV pool occupancy (the engine's HBM
            # footprint knob): same gauge families as `summary serve`
            "serve_engine": self._engine_gauges(),
        }

    def _summary_slo(self) -> dict:
        return {
            "slos": [dict(v) for v in self._slo_state.values()],
            "specs": [dict(s) for s in self._slo_specs],
        }

    async def h_dag_step(self, cid, conn, p):
        """A batch of compiled-DAG step flight records (fire-and-forget
        DAG_STEP frame from dag/executor.py, sent only while task events
        are on; the executor buffers ~16 steps per frame so the hot loop
        never pays a head wakeup per step).  Compiled steps never transit
        the scheduler, so these frames are their entire head-side
        footprint: join each record into the flight-record ring, the
        per-phase histograms, and the timeline — where h_timeline renders
        per-node dag_channel_wait / dag_exec / dag_push sub-spans exactly
        like the eager phases."""
        from ray_tpu._private import task_events

        dag_id = str(p.get("dag_id", ""))
        node_hex = bytes(p.get("node_id") or b"").hex()
        for step in p.get("steps", []):
            phases = {str(k): float(v) for k, v in (step.get("phases") or {}).items()}
            if not phases:
                continue
            name = f"dag:{step.get('name', 'node')}"
            step_id = f"{dag_id}:{int(step.get('seq', 0))}"
            durs = task_events.durations(phases)
            self.task_records.append(
                {
                    "task_id": step_id,
                    "name": name,
                    "node_id": node_hex,
                    "pid": int(step.get("pid", 0)),
                    "error": bool(step.get("error")),
                    "trace": {},
                    "phases": phases,
                    "durations": durs,
                }
            )
            for phase, dur in durs.items():
                self._observe_phase(phase, name, node_hex, dur)
            exec_start = phases.get("dag_exec_start", 0.0)
            self.timeline.append(
                {
                    "name": name,
                    "pid": int(step.get("pid", 0)),
                    "ts": exec_start,
                    "dur": max(0.0, phases.get("dag_exec_end", exec_start) - exec_start),
                    "error": bool(step.get("error")),
                    "trace": {},
                    "phases": phases,
                    "task_id": step_id,
                }
            )
        # ring occupancy samples piggyback the step batch (sampled at
        # flush time, ~16 steps apart — no extra frames on the hot loop)
        now = time.time()
        for ch in p.get("channels", []):
            key = str(ch.get("c", ""))
            if not key:
                continue
            stat = {
                "occupancy": int(ch.get("occ", 0)),
                "slots": int(ch.get("slots", 0)),
                "dag_id": dag_id,
                "ts": now,
            }
            self.dag_channel_stats[key] = stat
            self._set_gauge(
                "ray_tpu_dag_channel_occupancy",
                "Ring slots holding unconsumed steps (sampled per "
                "DAG_STEP flush)",
                {"channel": key},
                stat["occupancy"],
            )
            self._set_gauge(
                "ray_tpu_dag_channel_slots",
                "Ring capacity in slots",
                {"channel": key},
                stat["slots"],
            )
        return {}

    async def h_serve_trace(self, cid, conn, p):
        """A batch of serve request flight records (fire-and-forget
        SERVE_TRACE frame from serve/tracing.py, sent only while task
        events are on).  Joined exactly like task/dag records: the
        flight-record ring (name ``serve:<deployment>``), per-stage
        `ray_tpu_serve_request_seconds{stage,deployment}` histograms,
        first-class TTFT/TPOT distributions, and timeline sub-spans."""
        from ray_tpu._private import task_events

        node_hex = bytes(p.get("node_id") or b"").hex()
        for req in p.get("requests", []):
            phases = {str(k): float(v) for k, v in (req.get("phases") or {}).items()}
            if not phases:
                continue
            dep = str(req.get("deployment") or "deployment")
            name = f"serve:{dep}"
            durs = task_events.durations(phases)
            rec = {
                "task_id": "",
                "name": name,
                "node_id": node_hex,
                "pid": int(req.get("pid", 0)),
                "error": bool(req.get("error")),
                "trace": {
                    str(k): str(v) for k, v in (req.get("trace") or {}).items()
                },
                "phases": phases,
                "durations": durs,
                "ttft_s": req.get("ttft_s"),
                "tpot_s": req.get("tpot_s"),
                "tokens": int(req.get("tokens") or 0),
            }
            self.task_records.append(rec)
            for stage, dur in durs.items():
                if not stage.startswith("serve_"):
                    continue
                self._observe_hist(
                    task_events.SERVE_METRIC,
                    task_events.SERVE_METRIC_HELP,
                    task_events.SERVE_HISTOGRAM_BOUNDARIES,
                    {"stage": stage, "deployment": dep},
                    dur,
                )
            if rec["ttft_s"] is not None:
                self._observe_hist(
                    task_events.SERVE_TTFT_METRIC,
                    task_events.SERVE_TTFT_HELP,
                    task_events.SERVE_HISTOGRAM_BOUNDARIES,
                    {"deployment": dep},
                    float(rec["ttft_s"]),
                )
            if rec["tpot_s"] is not None:
                self._observe_hist(
                    task_events.SERVE_TPOT_METRIC,
                    task_events.SERVE_TPOT_HELP,
                    task_events.TPOT_HISTOGRAM_BOUNDARIES,
                    {"deployment": dep},
                    float(rec["tpot_s"]),
                )
            start = phases.get("serve_replica_recv") or phases.get("serve_proxy_recv", 0.0)
            end = phases.get("serve_handler_end", start)
            self.timeline.append(
                {
                    "name": name,
                    "pid": rec["pid"],
                    "ts": start,
                    "dur": max(0.0, end - start),
                    "error": rec["error"],
                    "trace": rec["trace"],
                    "phases": phases,
                    "task_id": "",
                }
            )
        return {}

    async def h_train_step(self, cid, conn, p):
        """A batch of train-step flight records plus the probe's rolling
        stats (fire-and-forget TRAIN_STEP frame from
        train/jax/step_probe.py).  Steps join the ring/timeline/
        histograms; the rolling stats become the jitter/MFU gauges the
        SLO watchdog and `ray-tpu summary train` read."""
        from ray_tpu._private import task_events

        node_hex = bytes(p.get("node_id") or b"").hex()
        run = str(p.get("name") or "train")
        name = f"train:{run}"
        for step in p.get("steps", []):
            phases = {str(k): float(v) for k, v in (step.get("phases") or {}).items()}
            if not phases:
                continue
            durs = task_events.durations(phases)
            self.task_records.append(
                {
                    "task_id": f"{run}:{int(step.get('seq', 0))}",
                    "name": name,
                    "node_id": node_hex,
                    "pid": int(step.get("pid", 0)),
                    "error": False,
                    "trace": {},
                    "phases": phases,
                    "durations": durs,
                }
            )
            for phase, dur in durs.items():
                if not phase.startswith("train_"):
                    continue
                self._observe_hist(
                    task_events.TRAIN_METRIC,
                    task_events.TRAIN_METRIC_HELP,
                    task_events.PHASE_HISTOGRAM_BOUNDARIES,
                    {"phase": phase, "name": run},
                    dur,
                )
            step_start = phases.get("train_step_start", 0.0)
            self.timeline.append(
                {
                    "name": name,
                    "pid": int(step.get("pid", 0)),
                    "ts": step_start,
                    "dur": max(
                        0.0, phases.get("train_step_end", step_start) - step_start
                    ),
                    "error": False,
                    "trace": {},
                    "phases": phases,
                    "task_id": f"{run}:{int(step.get('seq', 0))}",
                }
            )
        stats = p.get("stats") or {}
        if stats:
            stats = {str(k): v for k, v in stats.items()}
            stats["node"] = node_hex[:12]
            stats["ts"] = time.time()
            self.train_stats[run] = stats
            if "jitter_pct" in stats:
                self._set_gauge(
                    task_events.TRAIN_JITTER_METRIC,
                    task_events.TRAIN_JITTER_HELP,
                    {"name": run},
                    float(stats["jitter_pct"]),
                )
            if "mfu" in stats:
                self._set_gauge(
                    task_events.TRAIN_MFU_METRIC,
                    task_events.TRAIN_MFU_HELP,
                    {"name": run},
                    float(stats["mfu"]),
                )
        return {}

    def _chaos_emit(self, ev: dict):
        self._record_event("WARNING", "chaos", ev["message"], **ev["fields"])

    async def h_chaos_ctrl(self, cid, conn, p):
        """Runtime chaos arm/disarm from the driver, applied here and
        fanned out: live chaos-aware processes get the push on the
        "chaos" pubsub channel; late joiners read the KV entry at
        startup.  Runtime-armed plans are deliberately NOT WAL-persisted
        — a restarted head comes back fault-free unless env re-arms it."""
        import json as _json

        op = str(p.get("op", ""))
        if op == "arm":
            plan, seed = str(p.get("plan", "")), int(p.get("seed", 0))
            ctrl = {"op": "arm", "plan": plan, "seed": seed}
            chaos.apply_ctrl(ctrl)
            self.kv["chaos:plan"] = _json.dumps(ctrl).encode()
            self._record_event("WARNING", "chaos", f"chaos armed: {plan}", seed=seed)
        elif op == "disarm":
            chaos.apply_ctrl({"op": "disarm"})
            self.kv.pop("chaos:plan", None)
            self._record_event("INFO", "chaos", "chaos disarmed")
        elif op != "status":
            raise ValueError(f"unknown chaos op {op!r}")
        if op != "status":
            await self._publish(
                "chaos",
                {"op": op, "plan": str(p.get("plan", "")), "seed": int(p.get("seed", 0))},
            )
        return {"ok": True, "status": chaos.status()}

    # ------------------------------------------------- sampling profiler

    async def h_profile_ctrl(self, cid, conn, p):
        """Cluster-wide profiler control (util/profile_api.py): arm /
        disarm fan out exactly like chaos — applied here, stored in KV
        ``profile:ctrl`` for late joiners, pushed to live processes over
        the ``profile`` pubsub channel.  ``collect`` returns the folded
        stacks aggregated per (role, node); ``stacks`` broadcasts a
        one-shot native stack-dump request whose replies ``collect_stacks``
        then returns (`ray-tpu stacks`)."""
        import json as _json

        op = str(p.get("op", ""))
        if op == "arm":
            ctrl = {
                "op": "arm",
                "hz": int(p.get("hz") or RayConfig.profiler_hz),
                "roles": p.get("roles") or None,
                "deep": bool(p.get("deep")),
            }
            if p.get("clear", True):
                self._clear_profile_aggregation()
            self.profile_ctrl = ctrl
            _profiler.apply_ctrl(ctrl)
            self.kv["profile:ctrl"] = _json.dumps(ctrl).encode()
            self._record_event(
                "INFO",
                "profiler",
                f"profiler armed at {ctrl['hz']}Hz",
                hz=ctrl["hz"],
                roles=ctrl["roles"],
                deep=ctrl["deep"],
            )
            await self._publish("profile", ctrl)
        elif op == "disarm":
            self.profile_ctrl = None
            _profiler.apply_ctrl({"op": "disarm"})
            self.kv.pop("profile:ctrl", None)
            self._record_event("INFO", "profiler", "profiler disarmed")
            await self._publish("profile", {"op": "disarm"})
        elif op == "collect":
            out = {
                "stacks": {
                    f"{role}|{node}": dict(stacks)
                    for (role, node), stacks in self.profile_stacks.items()
                },
                "meta": {
                    f"{role}|{node}": dict(meta)
                    for (role, node), meta in self.profile_meta.items()
                },
            }
            if p.get("clear"):
                self._clear_profile_aggregation()
            return out
        elif op == "stacks":
            # one-shot native stack dump, cluster-wide: clear the last
            # harvest, dump this process in-band, fan the request out
            self.profile_stack_dumps = [
                {
                    "role": "head",
                    "pid": os.getpid(),
                    "node": self.head_node_id.hex()[:12],
                    "text": _profiler.dump_stacks(),
                }
            ]
            await self._publish("profile", {"op": "stacks"})
        elif op == "collect_stacks":
            return {"dumps": list(self.profile_stack_dumps)}
        elif op != "status":
            raise ValueError(f"unknown profile op {op!r}")
        agg = {
            f"{role}|{node}": {
                "samples": sum(stacks.values()),
                "distinct_stacks": len(stacks),
                **{
                    k: v
                    for k, v in self.profile_meta.get((role, node), {}).items()
                    if k in ("overhead_ratio", "idle", "hz")
                },
            }
            for (role, node), stacks in self.profile_stacks.items()
        }
        return {
            "ok": True,
            "armed": self.profile_ctrl is not None,
            "ctrl": dict(self.profile_ctrl) if self.profile_ctrl else None,
            "aggregate": agg,
            "local": _profiler.status(),
        }

    def _clear_profile_aggregation(self):
        self.profile_stacks.clear()
        self.profile_meta.clear()
        self.profile_slices.clear()

    async def h_profile_stats(self, cid, conn, p):
        """Fire-and-forget folded-stack delta (or stack-dump) frame from
        an armed process — one per flush window, never per sample."""
        self._ingest_profile_frame(p)
        return {}

    def _ingest_profile_frame(self, p: dict):
        node_raw = p.get("node_id")
        node = bytes(node_raw).hex()[:12] if node_raw else "local"
        role_proc = str(p.get("role", "?"))
        pid = int(p.get("pid") or 0)
        if "stack_dump" in p:
            if len(self.profile_stack_dumps) < 256:
                self.profile_stack_dumps.append(
                    {
                        "role": role_proc,
                        "pid": pid,
                        "node": node,
                        "text": str(p["stack_dump"]),
                    }
                )
            return
        stacks = p.get("stacks") or {}
        per_role: Dict[str, int] = {}
        for folded, n in stacks.items():
            folded = str(folded)
            # the stack's own root segment is its effective role: engine /
            # dashboard threads aggregate under their thread-role even
            # though the shipping process is a worker
            role = folded.split(";", 1)[0]
            n = int(n)
            per_role[role] = per_role.get(role, 0) + n
            bucket = self.profile_stacks.setdefault((role, node), {})
            bucket[folded] = bucket.get(folded, 0) + n
            if len(bucket) > RayConfig.profiler_max_stacks:
                self._trim_profile_bucket(role, bucket)
        for role, n in per_role.items():
            self._inc_counter(
                "ray_tpu_profiler_samples_total",
                "Wall-clock profiler stack samples aggregated at the head",
                {"role": role, "node": node},
                float(n),
            )
        wall = float(p.get("wall_s") or 0.0)
        if wall > 0:
            ratio = float(p.get("overhead_s") or 0.0) / wall
            self._set_gauge(
                "ray_tpu_profiler_overhead_ratio",
                "Fraction of wall time the armed sampler spends sampling "
                "(the ≤5% contract's numerator)",
                {"role": role_proc, "node": node},
                ratio,
            )
            # meta lands under every stack-root role this frame carried
            # (plus the process role): an engine/dashboard bucket's
            # sampler IS its host process's sampler, so its status row
            # must show that sampler's overhead/hz, not blanks
            for meta_role in set(per_role) | {role_proc}:
                meta = self.profile_meta.setdefault((meta_role, node), {})
                meta.update(
                    {
                        "overhead_ratio": ratio,
                        "idle": int(p.get("idle") or 0),
                        "hz": int(p.get("hz") or 0),
                        "pid": pid,
                    }
                )
        if per_role:
            top = sorted(stacks.items(), key=lambda kv: -int(kv[1]))[:5]
            self.profile_slices.append(
                {
                    "t0": float(p.get("t0") or time.time()),
                    "t1": float(p.get("t1") or time.time()),
                    "role": role_proc,
                    "node": node,
                    "pid": pid,
                    "samples": sum(per_role.values()),
                    "top": [[k, int(v)] for k, v in top],
                }
            )

    @staticmethod
    def _trim_profile_bucket(role: str, bucket: Dict[str, int]):
        """Cap a (role, node) bucket at profiler_max_stacks by folding the
        smallest counts into one <other> stack — sample totals stay exact,
        only the tail's split degrades."""
        keep = RayConfig.profiler_max_stacks * 3 // 4
        ranked = sorted(bucket.items(), key=lambda kv: -kv[1])
        spill = sum(n for _, n in ranked[keep:])
        bucket.clear()
        bucket.update(ranked[:keep])
        other = f"{role};<other>"
        bucket[other] = bucket.get(other, 0) + spill

    def _record_event(self, severity: str, source: str, message: str, **fields):
        self.events.append(
            {
                "timestamp": time.time(),
                "severity": severity,
                "source": source,
                "message": message,
                **fields,
            }
        )

    async def h_list_events(self, cid, conn, p):
        limit = int(p.get("limit", 1000))
        if limit <= 0:
            return {"events": []}
        return {"events": list(self.events)[-limit:]}

    async def h_record_event(self, cid, conn, p):
        """Remote processes (raylets, workers) append to the head's
        cluster-event ring (reference analog: src/ray/util/event.h events
        flowing to the dashboard event module)."""
        # sanitize remote-controlled fields: keys must be strings and must
        # not collide with the event envelope (severity/source/message/
        # timestamp), or the splat raises / silently rewrites history
        fields = {
            str(k): v
            for k, v in (p.get("fields") or {}).items()
            if str(k) not in ("severity", "source", "message", "timestamp")
        }
        self._record_event(
            str(p.get("severity", "INFO")),
            str(p.get("source", "remote")),
            str(p.get("message", "")),
            **fields,
        )
        return {"ok": True}

    async def h_list_objects(self, cid, conn, p):
        """Directory dump for `ray list objects` (reference analog:
        experimental/state/api.py:991 backed by the StateAggregator)."""
        import itertools

        limit = int(p.get("limit", 1000))
        out = []
        # safe to islice the live dict: this handler has no awaits inside
        # the loop, so nothing mutates the directory mid-iteration
        for oid, entry in itertools.islice(self.objects.items(), limit):
            spilled = self.object_spilled.get(oid)
            out.append(
                {
                    "object_id": oid,
                    "state": {PENDING: "PENDING", SEALED: "SEALED", ERRORED: "ERRORED"}[entry[0]],
                    "ref_count": self.object_refcounts.get(oid, 0),
                    "locations": [n.hex() for n in self.object_locations.get(oid, ())],
                    "spilled": bool(spilled),
                    "has_lineage": oid in self.lineage,
                }
            )
        return {"objects": out, "total": len(self.objects)}

    # timeline sub-span labels per flight-recorder duration (task_events
    # .DURATIONS keys); e2e spans both processes and stays implicit in the
    # submit→done stamps carried in args
    _TIMELINE_PHASES = (
        ("queue-wait", "head_enqueue", "dispatch"),
        ("deliver", "dispatch", "worker_dequeue"),
        ("arg-fetch", "arg_fetch_start", "arg_fetch_end"),
        ("exec", "exec_start", "exec_end"),
        ("put", "put_start", "put_end"),
        # compiled-DAG / serve-request / train-step records come straight
        # from the canonical phase vocabulary, so a phase added there can
        # never silently miss the timeline — records without the stamps
        # skip them
    ) + tuple(
        (name, start, end)
        for name, (start, end) in _task_events.DURATIONS.items()
        if name.startswith(("dag_", "serve_", "train_"))
    )

    async def h_timeline(self, cid, conn, p):
        """Chrome-trace events of recent task executions, nested per-phase
        sub-spans from the flight recorder, and cluster events (chaos
        faults, node/worker transitions) as instant markers — one view for
        fault → latency-spike causality
        (reference: `ray timeline` scripts.py → profile table dump)."""
        events = []
        for e in self.timeline:
            trace = e.get("trace") or {}
            events.append(
                {
                    "name": e["name"],
                    "cat": "task",
                    "ph": "X",
                    "ts": e["ts"] * 1e6,
                    "dur": e["dur"] * 1e6,
                    "pid": e["pid"],
                    "tid": e["pid"],
                    "args": {"error": e["error"], **trace},
                    "trace": trace,
                }
            )
            phases = e.get("phases") or {}
            for label, start, end in self._TIMELINE_PHASES:
                ts, te = phases.get(start), phases.get(end)
                if ts is None or te is None:
                    continue
                events.append(
                    {
                        "name": f"{e['name']}:{label}",
                        "cat": "task_phase",
                        "ph": "X",
                        "ts": ts * 1e6,
                        "dur": max(0.0, te - ts) * 1e6,
                        "pid": e["pid"],
                        "tid": e["pid"],
                        "args": {
                            "phase": label,
                            "task_id": e.get("task_id", ""),
                            **trace,
                        },
                        "trace": trace,
                    }
                )
        for ev in self.events:
            events.append(
                {
                    "name": f"{ev.get('source', '')}: {ev.get('message', '')}",
                    "cat": f"event:{ev.get('source', '')}",
                    "ph": "i",
                    "s": "g",
                    "ts": ev.get("timestamp", 0.0) * 1e6,
                    "pid": 0,
                    "tid": 0,
                    "args": {
                        k: v
                        for k, v in ev.items()
                        if k not in ("timestamp", "message", "source")
                    },
                }
            )
        # trace-stamped log records join the same view as instant markers:
        # "which line printed during which traced phase" without leaving
        # the timeline (records reach here via the logs pubsub transit)
        for rec in self._log_trace_marks:
            events.append(
                {
                    "name": f"log: {str(rec.get('msg', ''))[:120]}",
                    "cat": "log",
                    "ph": "i",
                    "s": "t",
                    "ts": (rec.get("ts") or 0.0) * 1e6,
                    "pid": rec.get("pid", 0),
                    "tid": rec.get("pid", 0),
                    "args": {
                        "msg": rec.get("msg", ""),
                        "stream": rec.get("stream", ""),
                        "node": rec.get("node", ""),
                        "task_id": rec.get("task", ""),
                        "trace_id": rec.get("trace", ""),
                    },
                    "trace": {"trace_id": rec.get("trace", "")},
                }
            )
        # sampled-stack slices (one per profiler flush window per process)
        # render as spans on the same view, so a queue-wait span and the
        # stacks that caused it sit side by side; args carry the window's
        # top folded stacks for drill-down
        for s in self.profile_slices:
            events.append(
                {
                    "name": f"profile:{s['role']}",
                    "cat": "profile",
                    "ph": "X",
                    "ts": s["t0"] * 1e6,
                    "dur": max(0.0, s["t1"] - s["t0"]) * 1e6,
                    "pid": s["pid"],
                    "tid": s["pid"],
                    "args": {
                        "role": s["role"],
                        "node": s["node"],
                        "samples": s["samples"],
                        "top_stacks": s["top"],
                    },
                }
            )
        return {"events": events}

    async def h_drain_node(self, cid, conn, p):
        nid = p["node_id"]
        await self._on_node_dead(nid)
        return {"ok": True}


    # -------------------------------------------------------------- scheduler

    def _kick_scheduler(self):
        self._sched_wakeup.set()

    def _task_resources(self, spec: TaskSpec) -> Dict[str, float]:
        return spec.resources or {"CPU": 1.0}

    def _release_creation_cpu(self, actor, node, spec: TaskSpec):
        """Give back the implicit creation CPU exactly once per actor
        incarnation (at ALIVE, or on death mid-creation — whichever comes
        first); explicit num_cpus and PG-bundle actors hold theirs."""
        if not getattr(spec, "implicit_cpu", False) or spec.pg_id or node is None:
            return
        if actor is not None:
            if actor.creation_cpu_released:
                return
            actor.creation_cpu_released = True
        cpu = (spec.resources or {"CPU": 1.0}).get("CPU", 0.0)
        if cpu > 0:
            node.release({"CPU": cpu})

    def _actor_lifetime_resources(self, spec: TaskSpec) -> Dict[str, float]:
        """What a LIVE actor holds: its declared resources, minus the
        creation-only implicit CPU (released at ALIVE; reference
        semantics: actors default to 0 CPU once running)."""
        res = dict(spec.resources or {"CPU": 1.0})
        if getattr(spec, "implicit_cpu", False) and not spec.pg_id:
            res.pop("CPU", None)
        return res

    def _release_task_resources(self, node: NodeInfo, spec: TaskSpec):
        res = self._task_resources(spec)
        if spec.pg_id and spec.pg_id in self.pgs:
            pg = self.pgs[spec.pg_id]
            idx = spec.pg_bundle_index if spec.pg_bundle_index >= 0 else 0
            if idx < len(pg.bundle_available):
                for k, v in res.items():
                    if v > 0:
                        pg.bundle_available[idx][k] = pg.bundle_available[idx].get(k, 0.0) + v
        else:
            node.release(res)

    def _pick_node(self, spec: TaskSpec) -> Optional[NodeInfo]:
        """Hybrid scheduling policy (reference:
        scheduling/policy/hybrid_scheduling_policy.h:48): pack onto the
        best-utilized feasible node while utilization < threshold, else
        spread to the least utilized."""
        res = self._task_resources(spec)
        if spec.pg_id:
            pg = self.pgs.get(spec.pg_id)
            if pg is None or pg.state != "CREATED":
                return None
            idx = spec.pg_bundle_index
            candidates = range(len(pg.bundles)) if idx < 0 else [idx]
            for i in candidates:
                nid = pg.bundle_nodes[i]
                node = self.nodes.get(nid) if nid else None
                if node is None or not node.alive:
                    continue
                av = pg.bundle_available[i]
                if all(av.get(k, 0.0) + 1e-9 >= v for k, v in res.items() if v > 0):
                    # consume from the bundle, not the node pool
                    for k, v in res.items():
                        if v > 0:
                            av[k] = av.get(k, 0.0) - v
                    spec.pg_bundle_index = i
                    return node
            return None
        if spec.node_affinity:
            node = self.nodes.get(spec.node_affinity)
            if node and node.alive and node.try_acquire(res):
                return node
            return None
        # decision + reservation in one native call (hybrid pack/spread)
        nid = self.sched.pick_and_acquire(
            res, RayConfig.scheduler_spread_threshold, prefer=self.head_node_id
        )
        if nid is None:
            return None
        return self.nodes.get(nid)

    async def _scheduler_loop(self):
        while not self._shutdown:
            self._sched_wakeup.clear()
            try:
                await self._schedule_once()
            except Exception:
                logger.exception("scheduler tick failed")
            try:
                await asyncio.wait_for(self._sched_wakeup.wait(), timeout=0.5)
                if len(self.task_queue) > 1024:
                    # genuinely deep backlog: let a few more completions
                    # land so one scan dispatches several workers' worth
                    # (amortizes the O(queue) pass).  Threshold matters:
                    # at >64 the sleep taxed every ~100-task burst (batch
                    # microbench 1390/s -> 772/s); longer sleeps measured
                    # worse too (workers idle waiting)
                    await asyncio.sleep(0.002)
            except asyncio.TimeoutError:
                pass

    async def _schedule_once(self):
        if self._recovery is not None:
            # recovery grace window: dispatch holds while live peers
            # re-attach — placing work on half-reconciled capacity could
            # double-book workers whose running tasks haven't been
            # re-announced yet (gcs/HEAD_FT.md)
            return
        # retry pending PGs (e.g. after resources freed / node added)
        for pg in self.pgs.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                self._try_place_pg(pg)
        # re-admit actors parked by preemption once capacity returns (and
        # no SLO-policy hold / queued higher-band work would immediately
        # re-evict them)
        if self._preempted_parked and not self._slo_preempt_hold:
            self._readmit_preempted()
        if not self.task_queue:
            return
        self._preempt_scans_left = 4  # bound victim-scan work per tick
        self._order_task_queue()
        remaining: List[TaskEntry] = []
        spawn_demand: Dict[bytes, int] = {}
        # dispatch-capacity snapshot, PER NODE: idle workers + spawnable
        # slots.  Once the cluster-wide total hits zero NOTHING can dispatch
        # this tick, so stop scanning — without this a deep backlog (10k+
        # queued) pays an O(queue) scan per tick, O(queue²) per drain
        # (measured 140s for a 10k drain).  Per-node counters (not one
        # global counter) so a backlog head pinned to one saturated node
        # cannot exhaust the budget and hide tasks placeable on OTHER idle
        # nodes in the same tick.  Counting is conservative (idle TPU
        # workers count as slots for CPU tasks), which only lengthens the
        # scan, never skips a dispatchable task.
        node_slots: Dict[bytes, int] = {}
        for node in self.nodes.values():
            if not node.alive:
                continue
            # O(1) from the idle index (was an O(workers) scan per tick)
            idle = len(node.idle_pool[False]) + len(node.idle_pool[True])
            limit = RayConfig.worker_startup_concurrency or max(
                2, int(node.resources_total.get("CPU", 2))
            )
            headroom = RayConfig.worker_pool_max_workers - len(node.workers)
            node_slots[node.node_id] = idle + max(
                0, min(headroom, limit) - node.starting_workers
            )
        total_slots = sum(node_slots.values())
        # tasks that reserved resources but found no idle worker this tick;
        # reservations are held until the end so demand is capped by what the
        # node can actually run simultaneously (not by queue length)
        unfulfilled: List[Tuple[TaskEntry, NodeInfo]] = []
        # bound the pick+release work spent skipping past a backlog pinned
        # to slot-exhausted nodes: past this many skips the rest of the
        # queue waits for the next tick (keeps a 10k-deep single-node
        # backlog from restoring the O(queue²) drain while another node
        # holds one idle slot)
        exhausted_skips = 64 + 8 * len(node_slots)
        # resource shapes that already failed placement THIS tick: within a
        # tick resources are only consumed (releases land after the loop),
        # so a failed shape cannot succeed later in the same scan — skip
        # the native pick for the rest of a deep homogeneous backlog
        # (measured: 430 failed pick_and_acquire calls per drained task
        # without this, the whole-queue rescan per tick)
        failed_shapes: set = set()
        for i, entry in enumerate(self.task_queue):
            if total_slots <= 0 or exhausted_skips <= 0:
                remaining.extend(self.task_queue[i:])
                break
            spec = entry.spec
            shape = None
            if not spec.pg_id and not spec.node_affinity:
                shape = entry.res_shape
                if shape is None:
                    shape = entry.res_shape = tuple(
                        sorted(self._task_resources(spec).items())
                    )
                if shape in failed_shapes:
                    remaining.append(entry)
                    continue
            node = self._pick_node(spec)
            if node is None:
                # Infeasible tasks stay pending — a node with the resources
                # may join later (reference semantics: raylet keeps
                # infeasible tasks queued and warns; the autoscaler reacts).
                if shape is not None:
                    failed_shapes.add(shape)
                # a band-above-floor request that cannot place may evict
                # lower-band work (victims die async; a later tick places us)
                if spec.priority > 0 and self._preempt_scans_left > 0:
                    self._maybe_preempt(entry)
                remaining.append(entry)
                continue
            if node_slots.get(node.node_id, 0) <= 0:
                # this node's dispatch capacity is spent for the tick, but
                # other nodes may still have slots: release the reservation
                # and keep scanning rather than burning the global budget
                self._release_task_resources(node, spec)
                # the release invalidates failed_shapes' only-consumed-
                # within-a-tick premise: a shape that failed while this
                # reservation was held may fit now — clear so it isn't
                # skipped for the rest of the scan (cost bounded by
                # exhausted_skips, which caps how often this branch runs)
                failed_shapes.clear()
                remaining.append(entry)
                exhausted_skips -= 1
                continue
            worker = self._find_idle_worker(node, spec)
            if worker is None:
                key = (node.node_id, self._needs_tpu(spec))
                spawn_demand[key] = spawn_demand.get(key, 0) + 1
                unfulfilled.append((entry, node))
                remaining.append(entry)
                node_slots[node.node_id] -= 1  # consumed a spawn slot
                total_slots -= 1
                continue
            await self._dispatch(entry, node, worker)
            node_slots[node.node_id] -= 1
            total_slots -= 1
        for entry, node in unfulfilled:
            self._release_task_resources(node, entry.spec)
        self.task_queue = remaining
        # spawn-ahead for queued actor creations: a creation blocked on
        # the creation CPU will need a fresh dedicated worker the moment a
        # slot frees — overlap the (slow) process spawn with the current
        # creations' startup instead of serializing spawn → create →
        # spawn.  Excess spawns become idle pool workers (reused by the
        # next creation or reaped on the idle timeout), so this only
        # pipelines work that is already committed.
        creation_backlog = sum(
            1
            for e in remaining
            if e.spec.task_type == ACTOR_CREATION_TASK and not self._needs_tpu(e.spec)
        )
        if creation_backlog:
            alive = [n for n in self.nodes.values() if n.alive]
            per_node = max(1, creation_backlog // max(1, len(alive)))
            for node in alive:
                idle_here = len(node.idle_pool[False])
                want = per_node - idle_here - node.starting_workers
                if want > 0:
                    spawn_demand[(node.node_id, False)] = max(
                        spawn_demand.get((node.node_id, False), 0),
                        node.starting_workers + want,
                    )
        for (nid, tpu), demand in spawn_demand.items():
            node = self.nodes.get(nid)
            if node is not None:
                self._maybe_spawn_worker(node, demand, tpu)

    @staticmethod
    def _needs_tpu(spec: TaskSpec) -> bool:
        return (spec.resources or {}).get(RayConfig.tpu_slice_resource_name, 0) > 0

    def _find_idle_worker(self, node: NodeInfo, spec: TaskSpec) -> Optional[WorkerInfo]:
        return node.pop_idle(self._needs_tpu(spec))

    def _maybe_spawn_worker(self, node: NodeInfo, demand: int = 1, tpu: bool = False):
        """Spawn workers up to current demand — the startup-token discipline
        of the reference's WorkerPool (worker_pool.cc:218
        StartWorkerProcess + MonitorStartingWorkerProcess:485).  Concurrent
        STARTS are capped at ~#CPUs (reference maximum_startup_concurrency):
        an uncapped 25-way python-import storm on a small host starves the
        running workers' heartbeats; the pending demand drains across ticks
        as registrations free tokens."""
        startup_limit = RayConfig.worker_startup_concurrency or max(
            2, int(node.resources_total.get("CPU", 2))
        )
        while node.starting_workers < min(demand, startup_limit):
            pool_size = len(node.workers) + node.starting_workers
            if pool_size >= RayConfig.worker_pool_max_workers:
                return
            node.starting_workers += 1
            if node.conn is None:
                self._spawn_local_worker(node, tpu)
            else:
                asyncio.get_running_loop().create_task(
                    node.conn.send(MsgType.PUSH_TASK, {"directive": "spawn_worker", "tpu": tpu})
                )

    def _spawn_local_worker(self, node: NodeInfo, tpu: bool = False):
        self._next_worker_seq += 1
        env = dict(os.environ)
        env.update(self._worker_env)
        env["RAY_TPU_HEAD"] = f"{self.host}:{self.port}"
        env["RAY_TPU_NODE_ID"] = node.node_id.hex()
        env["RAY_TPU_STORE_PATH"] = node.store_path
        # per-process chaos stream id: worker k's fault decisions come from
        # a distinct deterministic RNG stream (chaos.py stream_seed)
        env["RAY_TPU_CHAOS_NONCE"] = str(self._next_worker_seq)
        if tpu:
            # TPU worker: keep the ambient claim env (axon sitecustomize runs
            # at interpreter start and needs it) — this worker owns the chips
            env["RAY_TPU_WORKER_TPU"] = "1"
            env.pop("JAX_PLATFORMS", None)
        else:
            # pool workers must not tunnel-claim the TPU at import
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.pop("RAY_TPU_WORKER_TPU", None)
        log = os.path.join(self.session_dir, f"worker-head-{self._next_worker_seq}.log")
        if not tpu:
            # pool workers fork from the warm zygote (~30ms vs ~1s exec);
            # TPU workers keep exec (claim env needed at interpreter start).
            # The zygote pipe round trip is blocking — run it in a thread so
            # the event loop keeps serving RPCs (first spawn pays the
            # zygote's own ~1s preimport)
            if self._zygote is None:
                from ray_tpu._private.zygote import ZygoteSpawner

                self._zygote = ZygoteSpawner(
                    dict(env), os.path.join(self.session_dir, "zygote-head.log")
                )
            asyncio.get_running_loop().run_in_executor(
                None, self._spawn_pool_worker_blocking, env, log
            )
            return
        with open(log, "ab") as logf:
            subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.worker_main"],
                env=env,
                stdout=logf,
                stderr=logf,
                start_new_session=True,
            )

    def _spawn_pool_worker_blocking(self, env: dict, log: str):
        """Executor-thread body: zygote fork with exec fallback."""
        if self._zygote is not None and self._zygote.spawn(env, log) is not None:
            return
        try:
            with open(log, "ab") as logf:
                subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu.core.worker_main"],
                    env=env,
                    stdout=logf,
                    stderr=logf,
                    start_new_session=True,
                )
        except Exception:
            logger.exception("pool worker spawn failed")

    async def _dispatch(self, entry: TaskEntry, node: NodeInfo, worker: WorkerInfo):
        spec = entry.spec
        # fair-share: a dispatch drains a quantum from the job's deficit so
        # siblings in the same band take the next turns
        k = (spec.priority, bytes(spec.job_id or b""))
        d = self._job_deficit.get(k)
        if d is not None:
            self._job_deficit[k] = max(0.0, d - RayConfig.priority_fair_quantum_s)
        if spec.phases is not None:
            # shared with entry.wire (see h_submit_task), so the stamp
            # rides the cached PUSH_TASK frame to the worker
            spec.phases["dispatch"] = time.time()
        entry.state = "RUNNING"
        entry.worker_id = worker.worker_id
        entry.node_id = node.node_id
        node.mark_busy(worker)
        worker.running_tasks.add(spec.task_id)
        if spec.task_type == ACTOR_CREATION_TASK:
            worker.dedicated = True
            worker.actor_id = spec.actor_id
            actor = self.actors.get(spec.actor_id)
            if actor is not None:
                actor.worker_id = worker.worker_id
                actor.node_id = node.node_id
        try:
            # PG tasks re-encode: _pick_node may have just assigned the
            # bundle index, which the cached submit wire wouldn't carry
            wire = (
                entry.wire
                if entry.wire is not None and not spec.pg_id
                else spec.to_wire()
            )
            await worker.conn.send(MsgType.PUSH_TASK, {"spec": wire})
        except Exception:  # noqa: BLE001
            logger.warning(
                "task push to worker %s failed; declaring it dead",
                worker.worker_id.hex()[:8],
                exc_info=True,
            )
            await self._on_worker_dead(worker.worker_id, "push failed")

    # ----------------------------------- multi-tenant priorities / preemption

    def _order_task_queue(self):
        """Priority-aware dispatch order: higher bands first (with a
        one-band starvation boost once a task queues past
        ``priority_starvation_s``, so a starved low-band job still
        drains), weighted deficit fair-share within a band — each (band,
        job) accumulates queue-wait while it has work queued and a
        dispatch drains a quantum (``_dispatch``), so jobs that have
        waited longest take the next turns — and FIFO as the tiebreak.
        The single-tenant case (one band, one job) skips the sort: the
        queue stays the plain FIFO the drain-throughput work in
        ``_schedule_once`` was measured against."""
        q = self.task_queue
        now = time.time()
        dt = max(0.0, now - self._fair_tick_at)
        self._fair_tick_at = now
        keys = {(e.spec.priority, bytes(e.spec.job_id or b"")) for e in q}
        if len(keys) <= 1:
            if self._job_deficit:
                self._job_deficit = {
                    k: v for k, v in self._job_deficit.items() if k in keys
                }
            return
        # accumulate queue-wait once per (band, job) with work queued;
        # prune jobs whose queue drained (bounds the dict by live tenants)
        deficits = {k: v for k, v in self._job_deficit.items() if k in keys}
        for k in keys:
            deficits[k] = deficits.get(k, 0.0) + dt
        self._job_deficit = deficits
        starve = RayConfig.priority_starvation_s
        order = {id(e): i for i, e in enumerate(q)}

        def sort_key(e):
            band = e.spec.priority
            if starve > 0 and now - e.enqueued_at > starve:
                band += 1  # starvation boost: one band up, never unbounded
            return (
                -band,
                -deficits.get((e.spec.priority, bytes(e.spec.job_id or b"")), 0.0),
                order[id(e)],
            )

        q.sort(key=sort_key)

    def _readmit_preempted(self):
        """Respawn-with-restore: when a parked preempted actor's creation
        demand fits again and no queued higher-band work would immediately
        re-evict it, re-queue the creation task through the normal restart
        FSM (the worker restores from the saved checkpoint at creation).
        The fault-restart budget stays untouched — preemption is policy,
        not a fault."""
        # only FEASIBLE queued work counts against re-admission: a
        # permanently-infeasible high-band task (kept queued by design,
        # see _schedule_once) must not starve parked actors forever.
        # Fit answers are memoized per resource shape, so a deep
        # homogeneous backlog costs one total_fit plus a band-skip pass —
        # not the O(queue × nodes) scan the dispatch loop was
        # restructured to avoid.
        max_queued_band = -1
        shape_feasible: Dict[tuple, bool] = {}
        for e in self.task_queue:
            if e.spec.priority <= max_queued_band:
                continue
            shape = e.res_shape
            if shape is None:
                shape = tuple(sorted(self._task_resources(e.spec).items()))
            feas = shape_feasible.get(shape)
            if feas is None:
                res = dict(shape)
                feas = any(
                    n.alive and n.total_fit(res) for n in self.nodes.values()
                )
                shape_feasible[shape] = feas
            if feas:
                max_queued_band = e.spec.priority
        for aid in list(self._preempted_parked):
            actor = self.actors.get(aid)
            if actor is None or actor.state != ACTOR_PREEMPTED:
                self._preempted_parked.pop(aid, None)
                continue
            spec = actor.creation_spec
            if spec.priority < max_queued_band:
                continue  # higher-band work is still waiting for capacity
            res = self._task_resources(spec)
            if not any(
                n.alive and n.can_fit(res) for n in self.nodes.values()
            ):
                continue
            self._preempted_parked.pop(aid, None)
            self._requeue_actor_creation(actor)
            logger.info("re-admitting preempted actor %s", aid.hex()[:8])
            self._record_event(
                "INFO",
                "preempt",
                "actor re-admitted after preemption",
                actor_id=aid.hex(),
            )

    def _maybe_preempt(self, entry: TaskEntry) -> bool:
        """Victim selection for a band-N request that cannot place: find
        ONE node whose total capacity could hold the demand, walk its
        lower-band work bottom-up — idle preemptible-actor leases first
        (nothing in flight), then running best-effort tasks (kill +
        requeue on the preemption budget), then busy preemptible actors
        (checkpoint-respawn) — and evict the minimal prefix whose release
        covers the deficit.  All-or-nothing per node: freeing less than
        the demand would thrash lower bands without producing a
        placement."""
        now = time.time()
        save_deadline = RayConfig.actor_preempt_save_deadline_s
        if now - entry.preempt_requested_at < save_deadline + 2.0:
            return False  # victims from the last request may still be dying
        self._preempt_scans_left -= 1
        spec = entry.spec
        if spec.pg_id:
            return False  # PG demand is bundle-reserved; out of scope
        demand = self._task_resources(spec)
        band = spec.priority
        nodes = [n for n in self.nodes.values() if n.alive]
        if spec.node_affinity:
            nodes = [n for n in nodes if n.node_id == spec.node_affinity]
        # enumerate eligible victims ONCE cluster-wide, then node-filter
        # the (much smaller) candidate lists per node — not one full
        # actors+tasks table walk per node
        leases, idle_a, running, busy_a = self._victim_candidates(band)
        for node in nodes:
            if not node.total_fit(demand):
                continue
            nid = node.node_id
            cand = (
                [x for x in leases if x[1].node_id == nid],
                [x for x in idle_a if x[1].node_id == nid],
                [x for x in running if x[1].node_id == nid],
                [x for x in busy_a if x[1].node_id == nid],
            )
            victims = self._select_victims(node, band, demand, cand)
            if victims is None:
                continue
            entry.preempt_requested_at = now
            why = (
                f"band {band} "
                f"{spec.function_name or spec.method_name or 'task'} "
                "cannot place"
            )
            for kind, victim in victims:
                if kind == "task":
                    self._preempt_task_victim(victim, band, reason=why)
                elif kind == "lease":
                    self._revoke_lease(victim, band, reason=why)
                else:
                    self._spawn_actor_preempt(victim, band, reason=why)
            return True
        return False

    def _spawn_actor_preempt(
        self, actor: ActorInfo, band: int, reason: str = ""
    ) -> bool:
        """Reserve the victim SYNCHRONOUSLY (before the coroutine ever
        runs) and launch the checkpoint-respawn protocol.  Without the
        sync add, every victim scan in the same tick would re-count this
        actor's not-yet-released resources and over-evict elsewhere."""
        if actor.state != ACTOR_ALIVE or actor.actor_id in self._preempting:
            return False
        self._preempting.add(actor.actor_id)
        asyncio.get_running_loop().create_task(
            self._preempt_actor(actor, band, reason=reason)
        )
        return True

    def _victim_candidates(
        self, band: int, node_id: Optional[bytes] = None
    ) -> Tuple[List, List, List, List]:
        """Preemption-eligible work strictly below `band`, bucketed in
        the bottom-up eviction order — (cached worker leases, idle
        preemptible actors, running best-effort tasks, busy preemptible
        actors) — each entry a (victim_band, obj, releasable_resources)
        tuple, lowest band first.  Leases evict first: revocation is
        drain-and-return, the cheapest reclamation there is.  The ONE
        eligibility predicate shared by demand-driven victim selection
        and the SLO policy."""
        lease_bucket: List[Tuple[int, object, Dict[str, float]]] = []
        for lid, wid in self.leases.items():
            w = self.workers.get(wid)
            if w is None or w.lease is None or w.lease.get("revoking"):
                continue
            lband = int(w.lease.get("priority", 1))
            if lband >= band:
                continue
            if node_id is not None and w.node_id != node_id:
                continue
            lease_bucket.append((lband, w, dict(w.lease["resources"])))
        lease_bucket.sort(key=lambda x: x[0])
        idle_actors: List[Tuple[int, object, Dict[str, float]]] = []
        busy_actors: List[Tuple[int, object, Dict[str, float]]] = []
        running: List[Tuple[int, object, Dict[str, float]]] = []
        for actor in self.actors.values():
            cspec = actor.creation_spec
            if (
                actor.state != ACTOR_ALIVE
                or not cspec.preemptible
                or cspec.priority >= band
                or actor.actor_id in self._preempting
            ):
                continue
            if node_id is not None and actor.node_id != node_id:
                continue
            w = self.workers.get(actor.worker_id)
            if w is None:
                continue  # no process to strike
            release = self._actor_lifetime_resources(cspec)
            bucket = busy_actors if w.running_tasks else idle_actors
            bucket.append((cspec.priority, actor, release))
        for t in self.tasks.values():
            if (
                t.state != "RUNNING"
                or t.preempted
                or t.blocked
                or t.spec.task_type != NORMAL_TASK
                or t.spec.priority >= band
                or t.spec.pg_id
                or t.worker_id not in self.workers
            ):
                continue
            if node_id is not None and t.node_id != node_id:
                continue
            running.append((t.spec.priority, t, self._task_resources(t.spec)))
        for bucket in (idle_actors, running, busy_actors):
            bucket.sort(key=lambda x: x[0])  # lowest band evicted first
        return lease_bucket, idle_actors, running, busy_actors

    def _select_victims(
        self,
        node: NodeInfo,
        band: int,
        demand: Dict[str, float],
        candidates: Optional[Tuple[List, List, List, List]] = None,
    ) -> Optional[List[Tuple[str, object]]]:
        """Bottom-up victim set on one node covering `demand`'s deficit,
        or None when even evicting everything eligible wouldn't fit it.
        `candidates` is the node-filtered _victim_candidates tuple when
        the caller already enumerated cluster-wide."""
        avail = node.resources_available
        deficit = {
            k: v - avail.get(k, 0.0)
            for k, v in demand.items()
            if v > avail.get(k, 0.0) + 1e-9
        }
        if not deficit:
            return []  # already fits; nothing to evict
        leases, idle_actors, running, busy_actors = (
            candidates
            if candidates is not None
            else self._victim_candidates(band, node.node_id)
        )
        chosen: List[Tuple[str, object]] = []

        def take(cands, kind):
            for _, victim, release in cands:
                if not deficit:
                    return
                covers = False
                for k in list(deficit):
                    r = release.get(k, 0.0)
                    if r > 0:
                        covers = True
                        deficit[k] -= r
                        if deficit[k] <= 1e-9:
                            del deficit[k]
                if covers:
                    chosen.append((kind, victim))

        take(leases, "lease")  # cached worker leases: drain-and-return
        if deficit:
            take(idle_actors, "actor")  # idle leases: nothing in flight
        if deficit:
            take(running, "task")  # kill + requeue
        if deficit:
            take(busy_actors, "actor")  # checkpoint-respawn mid-work
        return None if deficit else chosen

    def _kill_worker_process(self, w: WorkerInfo, sig: int = 9):
        """Signal a worker process wherever it lives: os.kill reaches only
        this host, remote victims get a raylet directive.  An
        undeliverable directive (node gone, raylet conn dead) runs the
        worker-death path directly — a victim already marked preempted /
        PREEMPTED must not survive in name only, wedged out of both the
        victim scan and re-admission."""
        if w.node_id == self.head_node_id:
            try:
                os.kill(w.pid, sig)
            except OSError:
                pass
            return
        node = self.nodes.get(w.node_id)
        if node is None or node.conn is None:
            asyncio.get_running_loop().create_task(
                self._on_worker_dead(
                    w.worker_id, "kill directive undeliverable (node gone)"
                )
            )
            return

        async def _deliver():
            try:
                await node.conn.send(
                    MsgType.PUSH_TASK,
                    {"directive": "kill_worker", "pid": w.pid, "sig": sig},
                )
            except Exception:  # noqa: BLE001
                logger.warning(
                    "kill_worker directive to node %s failed; declaring "
                    "worker %s dead",
                    w.node_id.hex()[:8],
                    w.worker_id.hex()[:8],
                    exc_info=True,
                )
                await self._on_worker_dead(
                    w.worker_id, "kill directive failed (raylet conn)"
                )

        asyncio.get_running_loop().create_task(_deliver())

    def _preempt_task_victim(
        self, entry: TaskEntry, band: int, reason: str = ""
    ):
        w = self.workers.get(entry.worker_id)
        if w is None or entry.preempted:
            return
        entry.preempted = True
        self._record_preemption(
            "task",
            victim_band=entry.spec.priority,
            requester_band=band,
            name=entry.spec.function_name,
            victim=bytes(entry.spec.task_id).hex()[:16],
            reason=reason,
        )
        # SIGKILL the worker; _on_worker_dead sees entry.preempted and
        # requeues on the preemption budget (never the fault-retry budget)
        self._kill_worker_process(w, 9)

    async def _preempt_actor(
        self, actor: ActorInfo, band: int, reason: str = ""
    ):
        """The checkpoint-respawn protocol: PREEMPT_ACTOR → the actor's
        optional ``__ray_save__`` runs under
        ``actor_preempt_save_deadline_s`` (the checkpoint lands in head
        KV before the worker replies) → graceful release with NO
        restart-budget charge, parked for re-admission.  A failed, late,
        or missing reply escalates to SIGKILL through the normal fault
        path — restart budget charged, immediate requeue.

        Only entered via _spawn_actor_preempt, which already reserved
        this actor in _preempting (synchronously, so same-tick victim
        scans can't double-count its release); the reservation is
        released in the finally below — EXCEPT on the forced path, where
        it is held until the SIGKILL's death event lands
        (_on_actor_worker_dead discards), so the window between
        state=ALIVE and the worker actually dying can't be re-preempted
        into an uncharged graceful park."""
        keep_reserved = False
        try:
            if actor.state != ACTOR_ALIVE:
                return
            w = self.workers.get(actor.worker_id)
            if w is None:
                return
            deadline = RayConfig.actor_preempt_save_deadline_s
            # mark first: new calls queue in pending_calls instead of
            # racing onto a worker that is about to release
            actor.state = ACTOR_PREEMPTED
            try:
                reply = await w.conn.request(
                    MsgType.PREEMPT_ACTOR,
                    {"actor_id": actor.actor_id, "save_deadline_s": deadline},
                    timeout=deadline + 3.0,
                )
                ok = bool(reply.get("ok"))
            except Exception:  # noqa: BLE001
                logger.warning(
                    "PREEMPT_ACTOR save rpc to %s failed/timed out; "
                    "escalating to a budget-charged kill",
                    actor.actor_id.hex()[:8],
                    exc_info=True,
                )
                ok = False
            if actor.state != ACTOR_PREEMPTED:
                # destroyed or died while saving (preempt racing a
                # voluntary exit / ray.kill): the other transition owns
                # cleanup; do not park, do not kill twice
                return
            if ok:
                self._record_preemption(
                    "actor",
                    victim_band=actor.creation_spec.priority,
                    requester_band=band,
                    name=actor.creation_spec.function_name,
                    victim=actor.actor_id.hex()[:16],
                    reason=reason,
                )
            else:
                if actor.worker_id is None:
                    # the worker died on its own while we were saving and
                    # _on_actor_worker_dead already parked this PREEMPTED
                    # actor — leave that transition in charge (flipping to
                    # ALIVE here would strand a parked entry whose
                    # re-admission check silently drops it: a permanent
                    # ALIVE-with-no-worker wedge)
                    return
                # escalate: back to ALIVE so the death path charges the
                # restart budget and requeues immediately (fault FSM);
                # the _preempting reservation rides until that death event
                actor.state = ACTOR_ALIVE
                keep_reserved = True
                self._record_preemption(
                    "actor_forced",
                    victim_band=actor.creation_spec.priority,
                    requester_band=band,
                    name=actor.creation_spec.function_name,
                    victim=actor.actor_id.hex()[:16],
                    reason=(reason + "; __ray_save__ missed its deadline")
                    .strip("; "),
                )
            w2 = self.workers.get(actor.worker_id or b"")
            if w2 is not None:
                # checkpoint (if any) is already durable in head KV — the
                # worker's kv_put completed before its reply — so SIGKILL
                # is safe on both paths
                self._kill_worker_process(w2, 9)
        finally:
            if not keep_reserved:
                self._preempting.discard(actor.actor_id)

    def _record_preemption(
        self,
        kind: str,
        victim_band: int,
        requester_band: int,
        name: str = "",
        victim: str = "",
        reason: str = "",
    ):
        self._preempt_log.append(
            {
                "ts": time.time(),
                "kind": kind,
                "band": victim_band,
                "requester_band": requester_band,
                "name": name,
                "victim": victim,
                "reason": reason,
            }
        )
        self._record_event(
            "WARNING",
            "preempt",
            f"preempted {kind} {name or victim} "
            f"(band {victim_band} -> requester band {requester_band})"
            + (f": {reason}" if reason else ""),
            kind=kind,
            victim=victim,
        )
        self._inc_counter(
            "ray_tpu_preemptions_total",
            "Work evicted by the priority-preemptive scheduler, by victim "
            "band and kind (task / actor / actor_forced)",
            {"band": str(victim_band), "kind": kind},
        )

    def _inc_counter(self, metric, help_text, tags, inc: float = 1.0):
        """Head-owned counter series, same kv write-through as
        _set_gauge (deliberately not WAL-persisted)."""
        import json as _json

        from ray_tpu.util import metrics as metrics_mod

        key = f"metrics:{metric}:{metrics_mod.tag_string(tags)}:head"
        rec = self._counter_cache.get(key)
        if rec is None:
            rec = {
                "kind": "counter",
                "value": 0.0,
                "description": help_text,
                "tags": tags,
            }
            self._counter_cache[key] = rec
        rec["value"] += inc
        rec["ts"] = time.time()
        self.kv[key] = _json.dumps(rec).encode()

    def _summary_preemptions(self, limit: int = 0) -> dict:
        """Backend of `ray-tpu summary preemptions`: the rolling victim
        log, the counter families, parked actors, and the SLO hold."""
        counts: Dict[str, float] = {}
        prefix = "metrics:ray_tpu_preemptions_total:"
        for key, rec in self._counter_cache.items():
            if not key.startswith(prefix):
                continue
            tags = rec.get("tags") or {}
            counts[
                f"band={tags.get('band', '?')},kind={tags.get('kind', '?')}"
            ] = rec.get("value", 0.0)
        recs = list(self._preempt_log)
        return {
            "preemptions": recs[-limit:] if limit > 0 else recs,
            "counts": counts,
            "parked": [a.hex() for a in self._preempted_parked],
            "slo_hold": self._slo_preempt_hold,
            "total": len(recs),
        }

    def _summary_errors(self, limit: int = 0) -> dict:
        """Backend of `ray-tpu summary errors`: the signature-dedup view
        of the error ring — each distinct crash signature once, with
        first/last-seen and a count, newest-first — plus the counter
        family.  Dedup is the point: a hot loop throwing 10k times is ONE
        row with count=10000, not 10k rows."""
        counts: Dict[str, float] = {}
        prefix = "metrics:ray_tpu_error_records_total:"
        for key, rec in self._counter_cache.items():
            if not key.startswith(prefix):
                continue
            tags = rec.get("tags") or {}
            counts[f"kind={tags.get('kind', '?')}"] = rec.get("value", 0.0)
        groups = sorted(
            self._error_index.values(),
            key=lambda g: g.get("last_ts", 0.0),
            reverse=True,
        )
        if limit > 0:
            groups = groups[:limit]
        rows = []
        for g in groups:
            sample = g.get("sample") or {}
            rows.append(
                {
                    "signature": g["signature"],
                    "kind": g.get("kind", "task"),
                    "count": g.get("count", 0),
                    "first_ts": g.get("first_ts", 0.0),
                    "last_ts": g.get("last_ts", 0.0),
                    "exc_type": sample.get("exc_type", ""),
                    "message": sample.get("message", ""),
                    "name": sample.get("name", ""),
                    "last": sample,
                }
            )
        return {
            "errors": rows,
            "counts": counts,
            "distinct": len(self._error_index),
            "total": len(self.error_records),
        }

    def _apply_slo_policy(self, spec: dict, verdict: dict, now: float):
        """SLO → policy: a sustained burn on a spec carrying
        ``preempt_below_band`` evicts the lowest-band victim instead of
        merely emitting a breach marker, and holds re-admission of parked
        preempted work; recovery lifts the hold so it returns."""
        band = spec.get("preempt_below_band")
        if band is None:
            return
        name = spec["name"]
        if verdict["ok"]:
            if self._slo_breach_ticks.pop(name, None) is not None:
                if not self._slo_breach_ticks and self._slo_preempt_hold:
                    self._slo_preempt_hold = False
                    self._record_event(
                        "INFO",
                        "preempt",
                        f"slo {name} recovered: re-admitting preempted work",
                        slo=name,
                    )
            return
        ticks = self._slo_breach_ticks.get(name, 0) + 1
        self._slo_breach_ticks[name] = ticks
        if ticks < RayConfig.slo_preempt_sustain_ticks:
            return
        self._slo_preempt_hold = True
        if now - self._last_policy_preempt < RayConfig.slo_preempt_cooldown_s:
            return
        if self._policy_preempt(
            int(band), reason=f"slo {name} sustained burn"
        ):
            self._last_policy_preempt = now

    def _apply_slo_scale(self, spec: dict, verdict: dict, now: float):
        """Second SLO policy output (serve/FLEET.md): a sustained burn on
        a spec carrying ``scale_on_slo`` publishes a scale_out directive
        on the ``serve:fleet`` channel; sustained recovery unwinds the
        outstanding scale-outs one scale_in at a time (each retires a
        replica through the controller's graceful drain).  Directives,
        not RPCs: the head never blocks on the controller, and a
        controller mid-restart just misses one tick.  The controller
        clamps to [min_replicas, max_replicas] independently — the debt
        counter here only bounds directive EMISSION so recovery cannot
        drain below what the policy added."""
        sc = spec.get("scale_on_slo")
        if not isinstance(sc, dict) or not sc.get("deployment"):
            return
        name = spec["name"]
        dep = str(sc["deployment"])
        if verdict["ok"]:
            self._slo_scale_ticks.pop(name, None)
            if self._slo_scale_debt.get(name, 0) <= 0:
                self._slo_recover_ticks.pop(name, None)
                return
            rticks = self._slo_recover_ticks.get(name, 0) + 1
            self._slo_recover_ticks[name] = rticks
            if rticks < RayConfig.slo_scale_sustain_ticks:
                return
            if now - self._last_policy_scale.get(dep, 0.0) < RayConfig.slo_scale_cooldown_s:
                return
            self._slo_scale_debt[name] -= 1
            self._last_policy_scale[dep] = now
            self._emit_fleet_directive(
                "scale_in", dep, sc, slo=name, reason="slo recovered"
            )
            return
        self._slo_recover_ticks.pop(name, None)
        ticks = self._slo_scale_ticks.get(name, 0) + 1
        self._slo_scale_ticks[name] = ticks
        if ticks < RayConfig.slo_scale_sustain_ticks:
            return
        if now - self._last_policy_scale.get(dep, 0.0) < RayConfig.slo_scale_cooldown_s:
            return
        ceiling = max(
            0, int(sc.get("max_replicas", 8)) - int(sc.get("min_replicas", 1))
        )
        if self._slo_scale_debt.get(name, 0) >= ceiling:
            return  # policy already holds the spec's whole headroom
        self._slo_scale_debt[name] = self._slo_scale_debt.get(name, 0) + 1
        self._last_policy_scale[dep] = now
        self._emit_fleet_directive(
            "scale_out", dep, sc, slo=name, reason="sustained burn"
        )

    def _emit_fleet_directive(self, op: str, deployment: str, sc: dict, slo: str, reason: str):
        """Fire one serve:fleet directive + its timeline event.  Runs
        inside the observer loop on the head's event loop, so the publish
        is scheduled, never awaited — policy must not stall on a slow
        subscriber."""
        msg = {
            "op": op,
            "deployment": deployment,
            "min_replicas": int(sc.get("min_replicas", 1)),
            "max_replicas": int(sc.get("max_replicas", 8)),
            "slo": slo,
            "reason": reason,
        }
        asyncio.ensure_future(self._publish("serve:fleet", msg))
        self._record_event(
            "WARNING" if op == "scale_out" else "INFO",
            "serve_fleet",
            f"fleet directive {op}: {deployment} ({reason}, slo {slo})",
            deployment=deployment,
            op=op,
            slo=slo,
        )

    def _policy_preempt(self, band_below: int, reason: str) -> bool:
        """Evict ONE victim below `band_below`, lowest band first,
        bottom-up across the cluster (cached leases, idle preemptible
        actors, running tasks, busy preemptible actors)."""
        leases, idle_actors, running, busy_actors = self._victim_candidates(
            band_below
        )
        for cands, kind in (
            (leases, "lease"),
            (idle_actors, "actor"),
            (running, "task"),
            (busy_actors, "actor"),
        ):
            if not cands:
                continue
            victim = cands[0][1]
            if kind == "task":
                self._preempt_task_victim(victim, band_below, reason=reason)
            elif kind == "lease":
                self._revoke_lease(victim, band_below, reason=reason)
            else:
                self._spawn_actor_preempt(victim, band_below, reason=reason)
            return True
        return False

    # ---------------------------------------------------------- maintenance

    async def _memory_monitor_loop(self):
        """OOM policy: when this host's memory crosses the threshold, kill
        ONE worker running a retriable normal task per pass — never a
        task's last attempt, so forward progress survives sustained
        pressure (analog: reference raylet worker_killing_policy.cc
        retriable-FIFO policy + memory_monitor.py:94)."""
        interval = RayConfig.memory_monitor_interval_s
        if interval <= 0:
            return
        while not self._shutdown:
            await asyncio.sleep(interval)
            try:
                import psutil

                usage = psutil.virtual_memory().percent / 100.0
            except Exception:  # graftlint: disable=silent-except -- psutil is optional; without it the OOM monitor degrades to a no-op by design
                continue
            if os.environ.get("RAY_TPU_TEST_FORCE_MEMORY_PRESSURE"):
                usage = 1.0
            if usage < RayConfig.memory_usage_threshold:
                continue
            victim = None
            for entry in self.tasks.values():
                if (
                    entry.state == "RUNNING"
                    and entry.spec.task_type == NORMAL_TASK
                    and entry.spec.retries_left > 0
                    and entry.worker_id in self.workers
                    # os.kill only reaches THIS host: never signal a pid
                    # that belongs to a remote node's worker
                    and self.workers[entry.worker_id].node_id == self.head_node_id
                ):
                    victim = self.workers[entry.worker_id]
                    break
            if victim is None:
                continue
            logger.warning(
                "memory pressure %.0f%%: killing worker %s (task will retry)",
                usage * 100,
                victim.worker_id.hex()[:8],
            )
            self._record_event(
                "WARNING",
                "oom",
                f"memory pressure {usage:.0%}: killing retriable worker",
                worker_id=victim.worker_id.hex(),
            )
            try:
                os.kill(victim.pid, 9)
            except OSError:
                pass

    # ------------------------------------------- workload observer / SLOs

    _OBSERVER_PERIOD_S = 2.0

    async def _workload_observer_loop(self):
        """The workload-plane watchdog: every tick it (a) refreshes the
        cluster memory gauges (shm occupancy per node, object directory
        accounting, spill counters) and (b) evaluates the declared SLOs
        over rolling windows of the head's aggregated histograms.  SLO
        breaches land in the cluster-event ring (source ``slo`` — instant
        markers on the chrome timeline next to chaos events) and export
        ray_tpu_slo_ok / ray_tpu_slo_burn_rate gauges — the policy signal
        ROADMAP item 5's preemption/autoscaling consumes."""
        while not self._shutdown:
            await asyncio.sleep(self._OBSERVER_PERIOD_S)
            try:
                self._refresh_memory_gauges()
                self._evaluate_slos()
            except Exception:  # noqa: BLE001
                logger.exception("workload observer tick failed")

    # drop DAG channel samples this long after their last DAG_STEP flush:
    # channel keys embed a per-compile random id and the head never sees
    # DAG_TEARDOWN (it rides the direct-call conns), so without an age-out
    # every compile would leak a stats entry + two gauge series forever
    # and dead DAGs would scrape as live occupancy
    _DAG_CHANNEL_TTL_S = 60.0

    def _expire_dag_channel_stats(self):
        from ray_tpu.util import metrics as metrics_mod

        now = time.time()
        for key, stat in list(self.dag_channel_stats.items()):
            if now - float(stat.get("ts", 0.0)) <= self._DAG_CHANNEL_TTL_S:
                continue
            self.dag_channel_stats.pop(key, None)
            tag_str = metrics_mod.tag_string({"channel": key})
            self.kv.pop(
                f"metrics:ray_tpu_dag_channel_occupancy:{tag_str}:head", None
            )
            self.kv.pop(
                f"metrics:ray_tpu_dag_channel_slots:{tag_str}:head", None
            )

    def _refresh_memory_gauges(self):
        self._expire_dag_channel_stats()
        for nid, node in self.nodes.items():
            if not node.alive:
                continue
            stats = node.store_stats
            if nid == self.head_node_id and getattr(self, "_store", None):
                stats = {
                    "used": float(self._store.used()),
                    "capacity": float(self._store.capacity()),
                    "objects": float(self._store.num_objects()),
                    "evictions": float(self._store.evictions()),
                }
            if not stats:
                continue
            tags = {"node": nid.hex()[:12]}
            self._set_gauge(
                "ray_tpu_shm_used_bytes",
                "Bytes allocated in the node's shm object store",
                tags,
                stats.get("used", 0),
            )
            self._set_gauge(
                "ray_tpu_shm_capacity_bytes",
                "Capacity of the node's shm object store",
                tags,
                stats.get("capacity", 0),
            )
            self._set_gauge(
                "ray_tpu_shm_objects",
                "Objects resident in the node's shm store",
                tags,
                stats.get("objects", 0),
            )
            self._set_gauge(
                "ray_tpu_shm_evictions_total",
                "LRU evictions since the node's store was created",
                tags,
                stats.get("evictions", 0),
            )
        by_state = {"SEALED": 0, "PENDING": 0, "ERRORED": 0}
        for entry in self.objects.values():
            by_state[
                {PENDING: "PENDING", SEALED: "SEALED", ERRORED: "ERRORED"}[entry[0]]
            ] += 1
        for state, count in by_state.items():
            self._set_gauge(
                "ray_tpu_object_count",
                "Objects in the head directory by state",
                {"state": state},
                count,
            )
        self._set_gauge(
            "ray_tpu_object_pinned_count",
            "Objects with a positive cluster refcount",
            {},
            sum(1 for c in self.object_refcounts.values() if c > 0),
        )
        self._set_gauge(
            "ray_tpu_objects_spilled",
            "Objects whose only durable copy is a spill file",
            {},
            len(self.object_spilled),
        )
        self._set_gauge(
            "ray_tpu_device_object_count",
            "Objects resident in the device tier (HBM-pinned, zero shm copy)",
            {},
            len(self.device_objects),
        )
        self._set_gauge(
            "ray_tpu_device_object_bytes",
            "Array bytes pinned in the device tier across all holders",
            {},
            sum(
                int(r["meta"].get("nbytes", 0))
                for r in self.device_objects.values()
            ),
        )

    def _slo_metrics_view(self) -> Dict[str, dict]:
        """read_all()-shaped merged metrics with a "name" key per record
        (what SloEvaluator matches on)."""
        from ray_tpu.util import metrics as metrics_mod

        merged = metrics_mod.merge_series(
            metrics_mod.raw_records_from_kv(self.kv)
        )
        for key, rec in merged.items():
            rec["name"], _, _ = metrics_mod.parse_series_key(key)
        return merged

    def _evaluate_slos(self):
        import json as _json

        from ray_tpu._private import slo as slo_mod

        blob = self.kv.get("slo:specs")
        if blob != self._slo_specs_blob:
            self._slo_specs_blob = blob
            try:
                self._slo_specs = slo_mod.parse_specs(blob or b"[]")
            except (ValueError, TypeError) as e:
                logger.warning("invalid slo:specs ignored: %s", e)
                self._slo_specs = []
            live = {s["name"] for s in self._slo_specs}
            self._slo_evals = {
                name: ev for name, ev in self._slo_evals.items() if name in live
            }
            self._slo_state = {
                name: st for name, st in self._slo_state.items() if name in live
            }
            # a removed policy SLO must not pin the re-admission hold
            self._slo_breach_ticks = {
                n: t for n, t in self._slo_breach_ticks.items() if n in live
            }
            if not self._slo_breach_ticks:
                self._slo_preempt_hold = False
            # ...nor keep driving scale directives for a retired spec
            for st in (
                self._slo_scale_ticks,
                self._slo_recover_ticks,
                self._slo_scale_debt,
            ):
                for n in list(st):
                    if n not in live:
                        st.pop(n, None)
        if not self._slo_specs:
            return
        merged = self._slo_metrics_view()
        now = time.time()
        for spec in self._slo_specs:
            name = spec["name"]
            ev = self._slo_evals.get(name)
            if ev is None or ev.spec != spec:
                # new or changed spec: fresh evaluator (fresh window)
                ev = slo_mod.SloEvaluator(spec)
                self._slo_evals[name] = ev
            verdict = ev.evaluate(merged, now)
            prev_ok = self._slo_state.get(name, {}).get("ok", True)
            self._slo_state[name] = verdict
            self._set_gauge(
                "ray_tpu_slo_ok",
                "1 while the SLO holds over its rolling window",
                {"slo": name},
                1.0 if verdict["ok"] else 0.0,
            )
            self._set_gauge(
                "ray_tpu_slo_burn_rate",
                "Error-budget burn rate (1.0 consumes the budget exactly)",
                {"slo": name},
                float(verdict.get("burn_rate") or 0.0),
            )
            if prev_ok and not verdict["ok"]:
                self._record_event(
                    "WARNING",
                    "slo",
                    f"SLO breach: {name} "
                    f"value={verdict.get('value')} "
                    f"threshold={verdict.get('threshold')} "
                    f"burn_rate={verdict.get('burn_rate'):.2f}",
                    slo=name,
                    value=verdict.get("value"),
                    threshold=verdict.get("threshold"),
                    burn_rate=verdict.get("burn_rate"),
                )
            elif not prev_ok and verdict["ok"]:
                self._record_event(
                    "INFO",
                    "slo",
                    f"SLO recovered: {name}",
                    slo=name,
                    value=verdict.get("value"),
                )
            # policy output: sustained burn → preempt the lowest band;
            # recovery → lift the re-admission hold
            self._apply_slo_policy(spec, verdict, now)
            # second policy output: sustained burn → serve scale-out
            # directive; sustained recovery → scale-in (graceful drain)
            self._apply_slo_scale(spec, verdict, now)

    async def _idle_reaper_loop(self):
        while not self._shutdown:
            await asyncio.sleep(5.0)
            now = time.time()
            for node in self.nodes.values():
                idle = [
                    w
                    for w in node.workers.values()
                    if w.idle and not w.dedicated and now - w.idle_since > RayConfig.idle_worker_kill_s
                ]
                # keep a floor of warm workers
                keep = RayConfig.worker_pool_min_idle
                for w in idle[keep:]:
                    try:
                        os.kill(w.pid, 15)
                    except OSError:
                        pass

    _HANDLERS = {}


HeadServer._HANDLERS = {
    MsgType.REGISTER_NODE: HeadServer.h_register_node,
    MsgType.REGISTER_WORKER: HeadServer.h_register_worker,
    MsgType.REGISTER_JOB: HeadServer.h_register_driver,
    MsgType.HEARTBEAT: HeadServer.h_heartbeat,
    MsgType.DRAIN_NODE: HeadServer.h_drain_node,
    MsgType.SUBMIT_TASK: HeadServer.h_submit_task,
    MsgType.TASK_DONE: HeadServer.h_task_done,
    MsgType.CANCEL_TASK: HeadServer.h_cancel_task,
    MsgType.TASK_BLOCKED: HeadServer.h_task_blocked,
    MsgType.TASK_UNBLOCKED: HeadServer.h_task_unblocked,
    MsgType.CREATE_ACTOR: HeadServer.h_create_actor,
    MsgType.GET_ACTOR: HeadServer.h_get_actor,
    MsgType.KILL_ACTOR: HeadServer.h_kill_actor,
    MsgType.ACTOR_STATE: HeadServer.h_actor_state,
    MsgType.LIST_ACTORS: HeadServer.h_list_actors,
    MsgType.PUT_OBJECT: HeadServer.h_put_object,
    MsgType.WAIT_OBJECT: HeadServer.h_wait_object,
    MsgType.FREE_OBJECT: HeadServer.h_free_object,
    MsgType.ADD_REF: HeadServer.h_add_ref,
    MsgType.REMOVE_REF: HeadServer.h_remove_ref,
    MsgType.SPILL_NOTIFY: HeadServer.h_spill_notify,
    MsgType.LIST_OBJECTS: HeadServer.h_list_objects,
    MsgType.LIST_EVENTS: HeadServer.h_list_events,
    MsgType.RECORD_EVENT: HeadServer.h_record_event,
    MsgType.CHAOS_CTRL: HeadServer.h_chaos_ctrl,
    MsgType.SUBMIT_TASKS: HeadServer.h_submit_tasks,
    MsgType.CLIENT_PUT: HeadServer.h_client_put,
    MsgType.CLIENT_GET: HeadServer.h_client_get,
    MsgType.KV_PUT: HeadServer.h_kv_put,
    MsgType.KV_GET: HeadServer.h_kv_get,
    MsgType.KV_DEL: HeadServer.h_kv_del,
    MsgType.KV_KEYS: HeadServer.h_kv_keys,
    MsgType.KV_EXISTS: HeadServer.h_kv_exists,
    MsgType.SUBSCRIBE: HeadServer.h_subscribe,
    MsgType.PUBLISH: HeadServer.h_publish,
    MsgType.CREATE_PG: HeadServer.h_create_pg,
    MsgType.REMOVE_PG: HeadServer.h_remove_pg,
    MsgType.GET_PG: HeadServer.h_get_pg,
    MsgType.PG_READY: HeadServer.h_pg_ready,
    MsgType.LIST_PGS: HeadServer.h_list_pgs,
    MsgType.CLUSTER_RESOURCES: HeadServer.h_cluster_resources,
    MsgType.AVAILABLE_RESOURCES: HeadServer.h_available_resources,
    MsgType.LIST_NODES: HeadServer.h_list_nodes,
    MsgType.LIST_TASKS: HeadServer.h_list_tasks,
    MsgType.TIMELINE: HeadServer.h_timeline,
    MsgType.TASK_SUMMARY: HeadServer.h_task_summary,
    MsgType.DAG_STEP: HeadServer.h_dag_step,
    MsgType.SERVE_TRACE: HeadServer.h_serve_trace,
    MsgType.TRAIN_STEP: HeadServer.h_train_step,
    MsgType.LEASE_REQUEST: HeadServer.h_lease_request,
    MsgType.LEASE_RETURN: HeadServer.h_lease_return,
    MsgType.LEASE_NOTIFY: HeadServer.h_lease_notify,
    MsgType.TASK_STATS: HeadServer.h_task_stats,
    MsgType.PROFILE_CTRL: HeadServer.h_profile_ctrl,
    MsgType.PROFILE_STATS: HeadServer.h_profile_stats,
    MsgType.REATTACH: HeadServer.h_reattach,
    MsgType.LOG_FETCH: HeadServer.h_log_fetch,
    MsgType.ERROR_REPORT: HeadServer.h_error_report,
}
