"""GCS table persistence.

Analog of the reference's pluggable GCS storage
(reference: src/ray/gcs/gcs_server/gcs_table_storage.h over
store_client/redis_store_client.h:28 or in_memory_store_client.h:31).
This runtime's equivalent of "Redis mode" is a crash-consistent snapshot
file in the session dir: cluster metadata (KV, jobs, detached actors,
placement groups) survives a head restart, so detached actors are
re-reachable and get restarted on fresh workers — the head-FT behavior
the reference gets from HandleNotifyGCSRestart + Redis-backed tables.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional


class GcsSnapshotStorage:
    """Atomic write-then-rename snapshot of the GCS tables."""

    def __init__(self, path: str):
        self.path = path

    def save(self, tables: Dict[str, Any]):
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(tables, f, protocol=5)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except Exception:
            return None  # torn/corrupt snapshot: start fresh

    def delete(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass
