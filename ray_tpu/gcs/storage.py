"""GCS table persistence.

Analog of the reference's pluggable GCS storage
(reference: src/ray/gcs/gcs_server/gcs_table_storage.h over
store_client/redis_store_client.h:28 or in_memory_store_client.h:31).
This runtime's "Redis mode" is a base snapshot plus an APPEND-ONLY WAL
in the session dir: every table mutation (KV writes, detached actors,
placement groups, object directory, spill registry, lineage) appends a
framed record as it happens, and the snapshot is only rewritten when the
WAL grows past a threshold (compaction).  A restarted head replays
base+WAL, so it recovers to the last MUTATION, not the last snapshot
tick — including object locations and lineage, which makes post-restart
restoration of spilled objects and lineage reconstruction of evicted
ones possible (VERDICT r3 weak #8).
"""

from __future__ import annotations

import errno
import logging
import os
import pickle
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import chaos

logger = logging.getLogger(__name__)


class WalCorruptionError(Exception):
    """A WAL record is corrupt in the MIDDLE of a log (valid records
    follow it).  Unlike a torn tail — the expected shape of a crash mid-
    append, where truncating at the tear recovers every acknowledged
    record before it — skipping a mid-file record and applying later ones
    would replay mutations out of order (a kv delete before its put, a
    location update before the seal it follows).  The caller must fall
    back to snapshot-only recovery, loudly."""


class GcsSnapshotStorage:
    """Atomic write-then-rename snapshot of the GCS tables."""

    def __init__(self, path: str):
        self.path = path

    def save(self, tables: Dict[str, Any]):
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(tables, f, protocol=5)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except Exception:  # noqa: BLE001
            # torn/corrupt snapshot: start fresh — but say so, silently
            # dropping cluster state is how restarts "lose" actors
            logger.warning(
                "GCS snapshot %s is corrupt; starting fresh", self.path, exc_info=True
            )
            return None

    def delete(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass


class GcsWalStorage:
    """Base snapshot + append-only WAL of table mutations.

    Record framing: u32 length | u32 crc32 | pickle payload — a torn tail
    record (crash mid-append) is detected by the crc/length check and
    replay stops there, keeping every record before it.

    Durability boundary: every append is flushed to the OS immediately
    (survives process crash).  fsync is batched OFF the append path: the
    owner's periodic ``sync()`` (the GCS _persist_loop, 0.5s tick, run in
    a thread so head RPCs never stall behind disk latency) makes the tail
    durable — an OS/power loss can drop at most the final ~0.5s of
    mutations.  The reference's Redis mode has the same shape (redis
    appendfsync everysec, redis.conf default).
    """

    _HDR = struct.Struct("<II")

    def __init__(self, dir_path: str):
        self.base = GcsSnapshotStorage(os.path.join(dir_path, "gcs_base.pkl"))
        self.wal_path = os.path.join(dir_path, "gcs_wal.log")
        # a crash between begin_compact() (WAL rotated) and finish_compact()
        # (snapshot durable) leaves the rotated segment here; load() replays
        # it between the base and the live WAL
        self.rotated_path = self.wal_path + ".compacting"
        self._f = None
        self._last_fsync = 0.0
        self._fsync_pending = False
        self.wal_bytes = 0
        self.wal_records = 0

    def _open(self):
        if self._f is None:
            self._f = open(self.wal_path, "ab")
            self.wal_bytes = self._f.tell()
        return self._f

    def append(self, record: Tuple):
        payload = pickle.dumps(record, protocol=5)
        f = self._open()
        if chaos.disk_on:
            verdict = chaos.disk_decide("disk.wal.append")
            if verdict is not None:
                action, param = verdict
                if action == "delay":
                    time.sleep(param)  # slow-disk injection (sync path)
                elif action == "short":
                    # torn write: header + half the payload reach the disk
                    # (flushed — a kill inside this window leaves a genuine
                    # torn tail for replay's crc check), then the tear is
                    # truncated away before raising.  A SURVIVING process
                    # must not keep appending after torn bytes: replay stops
                    # at the first bad crc, so a mid-file tear would
                    # silently drop every later acknowledged record.
                    start = f.tell()
                    f.write(self._HDR.pack(len(payload), zlib.crc32(payload)))
                    f.write(payload[: len(payload) // 2])
                    f.flush()
                    f.truncate(start)
                    f.seek(start)
                    raise OSError(
                        errno.ENOSPC, "chaos: short WAL append (torn tail)"
                    )
                elif action == "fail":
                    raise OSError(errno.ENOSPC, "chaos: WAL append failed")
        f.write(self._HDR.pack(len(payload), zlib.crc32(payload)))
        f.write(payload)
        f.flush()
        self._fsync_pending = True
        self.wal_bytes += self._HDR.size + len(payload)
        self.wal_records += 1

    def sync(self):
        """Force any batched-but-unsynced appends to disk.  May run in a
        thread: the flag clears BEFORE the fsync so an append landing
        mid-fsync re-arms it (clearing after would mark that append
        durable without ever syncing it)."""
        if self._f is not None and self._fsync_pending:
            if chaos.disk_on:
                verdict = chaos.disk_decide("disk.wal.fsync")
                if verdict is not None:
                    action, param = verdict
                    if action == "delay":
                        time.sleep(param)  # slow fsync (runs off-loop)
                    elif action == "skip":
                        # silent durability hole: appends stay OS-buffered.
                        # _fsync_pending stays set so a later healthy sync
                        # still covers them.
                        return
                    elif action == "fail":
                        # before the flag clears: the owner's retry on the
                        # next tick re-attempts these appends
                        raise OSError(errno.EIO, "chaos: WAL fsync failed")
            self._fsync_pending = False
            os.fsync(self._f.fileno())
            self._last_fsync = time.monotonic()

    @classmethod
    def _replay_file(cls, path: str, records: List[Tuple]):
        """Replay one log file.  Corruption is treated POSITIONALLY:

        - a corrupt record at the very END of the file (short header/
          payload, or a crc mismatch with nothing after it) is a torn
          tail — the expected crash-mid-append shape.  The file is
          TRUNCATED at the tear (so later appends can never land behind
          garbage that replay would stop at) and the prefix is kept.
        - a corrupt record with valid bytes AFTER it is mid-file
          corruption: raising ``WalCorruptionError`` forces snapshot-only
          recovery instead of replaying a reordered suffix.
        """
        if not os.path.exists(path):
            return
        trunc_at = None
        with open(path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            while True:
                start = f.tell()
                hdr = f.read(cls._HDR.size)
                if not hdr:
                    break
                if len(hdr) < cls._HDR.size:
                    trunc_at = start  # torn header write at EOF
                    break
                length, crc = cls._HDR.unpack(hdr)
                payload = f.read(length)
                bad = len(payload) < length or zlib.crc32(payload) != crc
                decoded = None
                if not bad:
                    try:
                        decoded = pickle.loads(payload)
                    except Exception:  # graftlint: disable=silent-except -- undecodable == corrupt record; handled positionally below (truncate tail / raise WalCorruptionError)
                        bad = True  # crc-valid but undecodable: corrupt
                if bad:
                    if len(payload) == length and f.tell() < size:
                        raise WalCorruptionError(
                            f"{path}: corrupt record at offset {start} with "
                            f"{size - f.tell()} bytes following it — mid-file "
                            "corruption, refusing partial replay"
                        )
                    trunc_at = start
                    break
                records.append(decoded)
        if trunc_at is not None:
            logger.warning(
                "%s: torn tail record at offset %d truncated; %d records "
                "recovered before it",
                path,
                trunc_at,
                len(records),
            )
            with open(path, "r+b") as f:
                f.truncate(trunc_at)

    def load(self) -> Tuple[Optional[Dict[str, Any]], List[Tuple]]:
        """Restore (base tables, WAL records).  Raises WalCorruptionError
        on mid-file corruption — the caller decides whether to fall back
        to snapshot-only recovery (``self.base.load()``)."""
        tables = self.base.load()
        records: List[Tuple] = []
        self._replay_file(self.rotated_path, records)
        self._replay_file(self.wal_path, records)
        return tables, records

    def begin_compact(self, tables: Dict[str, Any]) -> bytes:
        """Phase 1 (call ON the mutation thread/loop): serialize the
        snapshot and rotate the WAL so new appends land in a fresh segment.
        Cheap relative to phase 2 — no data-file IO beyond the rotation.

        A leftover rotated segment (crash between the phases) is MERGED,
        not clobbered: its records are only durable there until some
        finish_compact lands a snapshot containing them, and the caller's
        `tables` does contain them (load() replayed the segment) — but if
        THIS compaction also crashes before phase 2, the disk must still
        hold every record."""
        snapshot = pickle.dumps(tables, protocol=5)
        if self._f is not None:
            if self._fsync_pending:
                # graftsan: disable=GS001 -- phase 1 runs on the persist loop by contract (see docstring): this fsync covers only appends since the last periodic sync, once per compaction
                os.fsync(self._f.fileno())
                self._fsync_pending = False
            self._f.close()
            self._f = None
        if os.path.exists(self.wal_path):
            if os.path.exists(self.rotated_path):
                with open(self.rotated_path, "ab") as dst, open(self.wal_path, "rb") as src:
                    while True:
                        chunk = src.read(1 << 20)
                        if not chunk:
                            break
                        dst.write(chunk)
                    dst.flush()
                    # graftsan: disable=GS001 -- crash-recovery merge of a leftover rotated segment (rare); durability before unlinking the live WAL is the invariant being bought
                    os.fsync(dst.fileno())
                os.unlink(self.wal_path)
            else:
                os.replace(self.wal_path, self.rotated_path)
        self.wal_bytes = 0
        self.wal_records = 0
        return snapshot

    def finish_compact(self, snapshot: bytes):
        """Phase 2 (safe OFF the loop — touches only the base file and the
        rotated segment, which the appender never writes): make the
        snapshot durable, then drop the folded-in WAL segment.

        Atomicity contract (chaos point ``disk.wal.compact``): any failure
        before the ``os.replace`` leaves the OLD base + the rotated
        segment intact, so a restart replays exactly the pre-compaction
        state; the rotated segment is only unlinked AFTER the new base is
        durable."""
        tmp = self.base.path + ".tmp"
        with open(tmp, "wb") as f:
            if chaos.disk_on:
                verdict = chaos.disk_decide("disk.wal.compact")
                if verdict is not None:
                    action, param = verdict
                    if action == "delay":
                        # graftsan: disable=GS001 -- chaos-injected stall, armed only in fault-injection runs; on-loop reachability is via the shutdown/restore composition (compact())
                        time.sleep(param)  # slow snapshot write (off-loop)
                    elif action == "short":
                        # torn snapshot write: half the bytes reach the tmp
                        # file, then ENOSPC — the tmp is abandoned, never
                        # renamed over the base
                        f.write(snapshot[: len(snapshot) // 2])
                        f.flush()
                        raise OSError(
                            errno.ENOSPC, "chaos: short compaction write"
                        )
                    elif action == "fail":
                        raise OSError(
                            errno.ENOSPC, "chaos: compaction write failed"
                        )
            f.write(snapshot)
            f.flush()
            # graftsan: disable=GS001 -- on-loop only via compact(), the shutdown/restore composition (loop is quiescing); steady-state compactions run phase 2 off the loop
            os.fsync(f.fileno())
        os.replace(tmp, self.base.path)
        try:
            os.unlink(self.rotated_path)
        except OSError:
            pass

    def compact(self, tables: Dict[str, Any]):
        """Fold the WAL into a fresh base snapshot and truncate it
        (synchronous composition of the two phases, for shutdown/restore)."""
        self.finish_compact(self.begin_compact(tables))

    def delete(self):
        self.base.delete()
        if self._f is not None:
            self._f.close()
            self._f = None
        for p in (self.wal_path, self.rotated_path):
            try:
                os.unlink(p)
            except OSError:
                pass
