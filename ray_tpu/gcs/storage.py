"""GCS table persistence.

Analog of the reference's pluggable GCS storage
(reference: src/ray/gcs/gcs_server/gcs_table_storage.h over
store_client/redis_store_client.h:28 or in_memory_store_client.h:31).
This runtime's "Redis mode" is a base snapshot plus an APPEND-ONLY WAL
in the session dir: every table mutation (KV writes, detached actors,
placement groups, object directory, spill registry, lineage) appends a
framed record as it happens, and the snapshot is only rewritten when the
WAL grows past a threshold (compaction).  A restarted head replays
base+WAL, so it recovers to the last MUTATION, not the last snapshot
tick — including object locations and lineage, which makes post-restart
restoration of spilled objects and lineage reconstruction of evicted
ones possible (VERDICT r3 weak #8).
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple


class GcsSnapshotStorage:
    """Atomic write-then-rename snapshot of the GCS tables."""

    def __init__(self, path: str):
        self.path = path

    def save(self, tables: Dict[str, Any]):
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(tables, f, protocol=5)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except Exception:
            return None  # torn/corrupt snapshot: start fresh

    def delete(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass


class GcsWalStorage:
    """Base snapshot + append-only WAL of table mutations.

    Record framing: u32 length | u32 crc32 | pickle payload — a torn tail
    record (crash mid-append) is detected by the crc/length check and
    replay stops there, keeping every record before it."""

    _HDR = struct.Struct("<II")

    def __init__(self, dir_path: str):
        self.base = GcsSnapshotStorage(os.path.join(dir_path, "gcs_base.pkl"))
        self.wal_path = os.path.join(dir_path, "gcs_wal.log")
        self._f = None
        self.wal_bytes = 0
        self.wal_records = 0

    def _open(self):
        if self._f is None:
            self._f = open(self.wal_path, "ab")
            self.wal_bytes = self._f.tell()
        return self._f

    def append(self, record: Tuple):
        payload = pickle.dumps(record, protocol=5)
        f = self._open()
        f.write(self._HDR.pack(len(payload), zlib.crc32(payload)))
        f.write(payload)
        f.flush()
        self.wal_bytes += self._HDR.size + len(payload)
        self.wal_records += 1

    def load(self) -> Tuple[Optional[Dict[str, Any]], List[Tuple]]:
        tables = self.base.load()
        records: List[Tuple] = []
        if os.path.exists(self.wal_path):
            with open(self.wal_path, "rb") as f:
                while True:
                    hdr = f.read(self._HDR.size)
                    if len(hdr) < self._HDR.size:
                        break
                    length, crc = self._HDR.unpack(hdr)
                    payload = f.read(length)
                    if len(payload) < length or zlib.crc32(payload) != crc:
                        break  # torn tail: stop at the last whole record
                    try:
                        records.append(pickle.loads(payload))
                    except Exception:
                        break
        return tables, records

    def compact(self, tables: Dict[str, Any]):
        """Fold the WAL into a fresh base snapshot and truncate it."""
        self.base.save(tables)
        if self._f is not None:
            self._f.close()
            self._f = None
        with open(self.wal_path, "wb"):
            pass
        self.wal_bytes = 0
        self.wal_records = 0

    def delete(self):
        self.base.delete()
        if self._f is not None:
            self._f.close()
            self._f = None
        try:
            os.unlink(self.wal_path)
        except OSError:
            pass
