"""Actor classes and handles.

Analog of the reference's ActorClass/ActorHandle/ActorMethod
(reference: python/ray/actor.py — ActorClass:161, _remote:657,
ActorMethod:82, ActorHandle:1021).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu._private.config import RayConfig
from ray_tpu._private.ids import ActorID, JobID
from ray_tpu.remote_function import _normalize_resources


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; use .remote()."
        )

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method_name, args, kwargs, self._num_returns)

    def bind(self, *args, **kwargs):
        """Declare this method as a node in a static dataflow graph
        (compiled actor DAGs, ray_tpu/dag/).  Args may be other bound
        nodes, an InputNode, or plain constants; nothing executes until
        the graph is compiled and driven with ``compiled.execute()``."""
        from ray_tpu.dag.node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def options(self, num_returns: int = 1, **_):
        return ActorMethod(self._handle, self._method_name, num_returns)


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str, function_id: bytes, core_worker):
        self._actor_id = actor_id
        self._class_name = class_name
        self._function_id = function_id
        self._cw = core_worker

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def _invoke(self, method_name: str, args, kwargs, num_returns: int):
        from ray_tpu._private import worker as worker_mod

        cw = worker_mod._require_connected()
        refs = cw.submit_actor_task(
            actor_id=self._actor_id,
            function_id=self._function_id,
            method_name=method_name,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
        )
        return refs[0] if num_returns == 1 else refs

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:8]}…)"

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id, self._class_name, self._function_id))

    @classmethod
    def _from_spec(cls, spec, cw):
        return cls(spec.actor_id, spec.function_name, spec.function_id, cw)


def _rebuild_handle(actor_id: bytes, class_name: str, function_id: bytes) -> ActorHandle:
    from ray_tpu._private import worker as worker_mod

    cw = worker_mod.global_worker.core_worker
    return ActorHandle(actor_id, class_name, function_id, cw)


class ActorClass:
    def __init__(self, cls: type, options: Optional[dict] = None):
        self._cls = cls
        self._options = options or {}
        self._function_id = None
        self._exported_by = None
        self.__name__ = cls.__name__

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._options)

    def __reduce__(self):
        return (ActorClass, (self._cls, self._options))

    def options(self, **new_options):
        merged = {**self._options, **new_options}
        parent = self

        class _Wrapped:
            def remote(self, *args, **kwargs):
                return parent._remote(args, kwargs, merged)

        return _Wrapped()

    def _remote(self, args, kwargs, opts) -> ActorHandle:
        from ray_tpu._private import worker as worker_mod

        if opts.get("preemptible"):
            # checkpoint-respawn preemption relies on the sequential
            # actor's one-call-at-a-time lock to fence __ray_save__
            # against in-flight calls; concurrent/async actors run
            # methods outside that lock, so a snapshot could be taken
            # mid-call and acknowledged results silently rolled back on
            # restore — reject loudly instead
            import inspect as _inspect

            if opts.get("max_concurrency", 1) > 1:
                raise ValueError(
                    "preemptible=True requires a sequential actor "
                    "(max_concurrency=1): the checkpoint fence cannot "
                    "cover concurrent method execution"
                )
            if any(
                _inspect.iscoroutinefunction(m)
                for _, m in _inspect.getmembers(
                    self._cls, predicate=_inspect.isfunction
                )
            ):
                raise ValueError(
                    "preemptible=True is not supported for async actors: "
                    "methods run on the actor's event loop outside the "
                    "checkpoint fence"
                )
        cw = worker_mod._require_connected()
        if self._function_id is None or self._exported_by is not cw:
            self._function_id, _ = cw.export_function(self._cls)
            self._exported_by = cw
        actor_id = ActorID.of(cw.job_id).binary()
        pg = opts.get("placement_group")
        pg_id = None
        bundle_index = opts.get("placement_group_bundle_index", -1)
        if pg is not None:
            pg_id = pg.id if isinstance(pg.id, bytes) else pg.id.binary()
        scheduling_strategy = opts.get("scheduling_strategy")
        node_affinity = None
        if scheduling_strategy is not None and hasattr(scheduling_strategy, "node_id"):
            # NodeAffinitySchedulingStrategy (reference:
            # util/scheduling_strategies.py) — pin the actor to a node
            if getattr(scheduling_strategy, "soft", False):
                raise ValueError(
                    "NodeAffinitySchedulingStrategy(soft=True) is not "
                    "supported: affinity here is a hard pin (a soft task "
                    "would silently hang pinned to a dead node)"
                )
            node_affinity = bytes.fromhex(scheduling_strategy.node_id)
        if scheduling_strategy is not None and hasattr(scheduling_strategy, "placement_group"):
            spg = scheduling_strategy.placement_group
            if spg is not None:
                pg_id = spg.id if isinstance(spg.id, bytes) else spg.id.binary()
                bundle_index = getattr(
                    scheduling_strategy, "placement_group_bundle_index", -1
                )
        lifetime = opts.get("lifetime")
        cw.create_actor(
            actor_id=actor_id,
            function_id=self._function_id,
            class_name=self._cls.__name__,
            args=args,
            kwargs=kwargs,
            resources=_normalize_resources(
                opts.get("num_cpus"), opts.get("num_tpus"), opts.get("resources")
            ),
            # default-CPU actors: 1 CPU to schedule creation, 0 held while
            # running (reference actor semantics)
            implicit_cpu=opts.get("num_cpus") is None,
            max_restarts=opts.get("max_restarts", RayConfig.actor_max_restarts),
            max_concurrency=opts.get("max_concurrency", 1),
            name=opts.get("name", ""),
            namespace=opts.get("namespace", ""),
            detached=(lifetime == "detached"),
            pg_id=pg_id,
            pg_bundle_index=bundle_index,
            runtime_env=opts.get("runtime_env"),
            node_affinity=node_affinity,
            # multi-tenant band (None -> the driver's job-level priority);
            # preemptible opts in to checkpoint-respawn eviction via the
            # optional __ray_save__/__ray_restore__ hooks
            priority=opts.get("priority"),
            preemptible=bool(opts.get("preemptible", False)),
        )
        return ActorHandle(actor_id, self._cls.__name__, self._function_id, cw)
