"""Request batching: coalesce concurrent calls into one model invocation.

Analog of the reference's @serve.batch (reference: python/ray/serve/
batching.py:46 _BatchQueue, :87 wait_for_batch, :131 decorator).  The
TPU angle: a jitted model wants fixed large batches — callers trickle in
single requests, the queue release them as one padded tensor batch.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(
        self,
        fn,
        max_batch_size: int,
        batch_wait_timeout_s: float,
        max_pending: Optional[int] = None,
    ):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.max_pending = max_pending
        self.queue: List = []  # [(item, future)]
        self._flusher: Optional[asyncio.Task] = None

    def depth(self) -> int:
        return len(self.queue)

    async def submit(self, instance, item):
        from ray_tpu.serve import tracing as serve_tracing

        if self.max_pending is not None and len(self.queue) >= self.max_pending:
            # bounded failure mode for the static path too: reject at
            # submit (the proxy's 503) instead of queueing unboundedly
            from ray_tpu.exceptions import EngineOverloadedError

            raise EngineOverloadedError(
                f"batch queue full ({self.max_pending} waiting)",
                retry_after_s=max(self.timeout, 0.05) * 4,
            )
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # capture the submitting request's trace record NOW (submit runs
        # on the request's own context); the flusher task stamps it later
        trace = serve_tracing.current_request()
        serve_tracing.stamp(trace, "serve_queue_enter")
        self.queue.append((item, fut, trace))
        if len(self.queue) >= self.max_batch_size:
            await self._flush(instance)
        elif self._flusher is None or self._flusher.done():
            self._flusher = loop.create_task(self._timed_flush(instance))
        return await fut

    async def _timed_flush(self, instance):
        await asyncio.sleep(self.timeout)
        await self._flush(instance)

    async def _flush(self, instance):
        from ray_tpu.serve import tracing as serve_tracing

        if not self.queue:
            return
        batch, self.queue = self.queue, []
        items = [b[0] for b in batch]
        futs = [b[1] for b in batch]
        traces = [b[2] for b in batch if b[2] is not None]
        for tr in traces:
            serve_tracing.stamp(tr, "serve_queue_exit")
        try:
            # batch_scope: the model invocation below stamps assembly /
            # prefill / decode onto every coalesced request via stamp_batch
            with serve_tracing.batch_scope(traces):
                if instance is not None:
                    results = self.fn(instance, items)
                else:
                    results = self.fn(items)
                if asyncio.iscoroutine(results):
                    results = await results
            if len(results) != len(items):
                raise ValueError(
                    f"batched fn returned {len(results)} results for {len(items)} inputs"
                )
            for fut, res in zip(futs, results):
                if not fut.done():
                    fut.set_result(res)
        except BaseException as e:  # noqa: BLE001
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)


def batch(
    _fn=None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
    max_pending: Optional[int] = None,
):
    """Decorator: async method taking a single item → coalesced list calls.

    The wrapped function must accept a LIST of items and return a LIST of
    results (reference semantics).  ``max_pending`` bounds the waiting
    queue: overflow raises EngineOverloadedError at submit (the HTTP
    proxy maps it to 503 + Retry-After); None keeps the legacy unbounded
    behavior."""

    def deco(fn):
        queue = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s, max_pending)

        @functools.wraps(fn)
        async def wrapper(self_or_item, *args):
            # method form: wrapper(self, item); function form: wrapper(item)
            if args:
                return await queue.submit(self_or_item, args[0])
            return await queue.submit(None, self_or_item)

        wrapper._batch_queue = queue
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
