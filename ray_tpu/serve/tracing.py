"""Serve request tracing: per-stage spans from ingress to last token.

Extends the task flight recorder (_private/task_events.py) to the serve
plane (reference analogs: the reference's serve request-context
propagation, python/ray/serve/_private/replica.py request metadata +
handle_request_streaming latency metrics; and vLLM-style TTFT/TPOT
accounting for LLM serving).  A request record is born at the ingress
(HTTP proxy or a bare DeploymentHandle), rides the call as a reserved
kwarg (``_serve_trace``) into the replica, picks up replica-side stamps
(queue wait, batch assembly, prefill, decode), and ships to the head on
a fire-and-forget ``SERVE_TRACE`` frame — batched like DAG_STEP, never a
per-request head round trip.  The head joins records next to the task
flight records: same ring, same timeline, per-stage
``ray_tpu_serve_request_seconds{stage,deployment}`` histograms, plus
first-class TTFT/TPOT distributions for the LLM path.

Stage stamps come from the canonical ``task_events.PHASES`` vocabulary
(the ``serve_*`` block — graftlint GL008 checks literal stamp sites).

Overhead contract: when recording is off (``RAY_TPU_TASK_EVENTS=0``)
``new_request()`` returns None after one flag check, and every
downstream site gates on that None — no dict, no clock read, no extra
wire bytes (the reserved kwarg is only attached when a record exists).

Propagation inside the replica uses contextvars, so the batch queue and
the model engine stamp the right request(s) without threading a handle
through every call: ``request_scope`` installs the in-flight record,
``batch_scope`` installs the list of records coalesced into one model
invocation (``stamp_batch`` fans a stamp out to all of them).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private import task_events

# the request currently being handled on this (asyncio) context
_current_request: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "serve_request_trace", default=None
)
# the requests coalesced into the model batch currently executing
_current_batch: contextvars.ContextVar[Optional[List[dict]]] = contextvars.ContextVar(
    "serve_batch_traces", default=None
)


def enabled() -> bool:
    return task_events.enabled


def new_request(deployment: str = "") -> Optional[dict]:
    """Fresh request record, or None when recording is off (the one flag
    check every downstream stamp site gates on)."""
    if not task_events.enabled:
        return None
    from ray_tpu.util import tracing as span_tracing

    return {
        "deployment": deployment,
        "phases": {"serve_proxy_recv": time.time()},
        "trace": span_tracing.new_span_context() or {},
        "tokens": 0,
        "error": False,
    }


def stamp(trace: Optional[dict], phase: str) -> None:
    if trace is not None:
        trace["phases"][phase] = time.time()


def current_request() -> Optional[dict]:
    return _current_request.get()


@contextlib.contextmanager
def request_scope(trace: Optional[dict]):
    """Replica-side: install the in-flight request's record so the batch
    queue (and anything else downstream) can stamp it."""
    token = _current_request.set(trace)
    try:
        yield trace
    finally:
        _current_request.reset(token)


@contextlib.contextmanager
def batch_scope(traces: List[dict]):
    """Around one coalesced model invocation: ``stamp_batch`` inside the
    scope stamps every request in the batch."""
    token = _current_batch.set(traces)
    try:
        yield traces
    finally:
        _current_batch.reset(token)


def batch_active() -> bool:
    return bool(_current_batch.get())


def stamp_batch(phase: str) -> None:
    """Stamp `phase` on every request record in the executing batch (a
    no-op outside a batch_scope / with recording off)."""
    traces = _current_batch.get()
    if not traces:
        return
    now = time.time()
    for tr in traces:
        tr["phases"][phase] = now


def set_batch_tokens(n: int) -> None:
    """Record how many tokens each request in the batch received (the
    TPOT denominator)."""
    traces = _current_batch.get()
    if not traces:
        return
    for tr in traces:
        tr["tokens"] = int(n)


def derive(trace: dict) -> dict:
    """TTFT/TPOT for a sealed record: TTFT = receipt → first token; TPOT
    = decode window / (tokens - 1).  None when the path never generated
    (non-LLM deployments lack the prefill/decode stamps)."""
    ph = trace["phases"]
    out = {"ttft_s": None, "tpot_s": None}
    first = ph.get("serve_first_token")
    start = ph.get("serve_proxy_recv") or ph.get("serve_replica_recv")
    if first is not None and start is not None:
        out["ttft_s"] = max(0.0, first - start)
    decode_end = ph.get("serve_decode_end")
    tokens = int(trace.get("tokens") or 0)
    if first is not None and decode_end is not None and tokens > 1:
        out["tpot_s"] = max(0.0, decode_end - first) / (tokens - 1)
    return out


# ------------------------------------------------- replica-side shipping
# Batched fire-and-forget, mirroring dag/executor.py's DAG_STEP buffering
# (reference analog: task_event_buffer.cc flushes on size/staleness,
# never per event).

_BATCH = 8
_FLUSH_S = 0.25
_buf_lock = threading.Lock()
_buf: List[dict] = []
_last_flush = 0.0


def defer_finish(trace: Optional[dict]) -> None:
    """Hand sealing ownership to a later finisher: the continuous-batching
    engine's requests OUTLIVE the actor method that submitted them (the
    handler returns while tokens still stream), so the replica's
    handle_request ``finally`` must not seal the record — the engine does,
    at retirement, with ``finish_request(trace, final=True)``."""
    if trace is not None:
        trace["_deferred"] = True


def finish_request(trace: Optional[dict], error: bool = False, final: bool = False) -> None:
    """Seal a request record (stamps serve_handler_end, derives
    TTFT/TPOT) and buffer it; a full or stale buffer ships as one
    SERVE_TRACE frame.  Idempotent: a record seals exactly once (the
    engine path has two finishers — the submitting handler's ``finally``
    and the engine's retirement — ``_deferred``/``_sealed`` arbitrate)."""
    global _buf, _last_flush
    if trace is None or trace.get("_sealed"):
        return
    if trace.get("_deferred") and not final:
        return  # the engine owns this record's seal
    trace["_sealed"] = True
    trace["phases"]["serve_handler_end"] = time.time()
    trace["error"] = bool(error)
    trace.update(derive(trace))
    trace["pid"] = os.getpid()
    # internal arbitration keys never ship
    record = {k: v for k, v in trace.items() if not k.startswith("_")}
    with _buf_lock:
        _buf.append(record)
        now = record["phases"]["serve_handler_end"]
        if len(_buf) < _BATCH and now - _last_flush < _FLUSH_S:
            return
        batch, _buf = _buf, []
        _last_flush = now
    _ship(batch)


def flush() -> None:
    """Ship whatever records remain (tests / replica teardown)."""
    global _buf
    with _buf_lock:
        batch, _buf = _buf, []
    if batch:
        _ship(batch)


def _ship(batch: List[dict]) -> None:
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.protocol import MsgType

    try:
        cw = worker_mod._require_connected()
        cw.io.spawn(
            cw.conn.send(
                MsgType.SERVE_TRACE,
                {"node_id": cw.node_id, "requests": batch},
            )
        )
    except Exception:  # graftlint: disable=silent-except -- observability is best-effort; the request result already left
        pass
