"""Public Serve API: @deployment, run, shutdown, handles, HTTP ingress.

Analog of the reference's serve.api (reference: python/ray/serve/api.py:455
serve.run; @serve.deployment decorator api.py; HTTP proxy
_private/http_proxy.py:189 — here an aiohttp actor per cluster).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

CONTROLLER_NAME = "_serve_controller"


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    route_prefix: Optional[str] = None
    ray_actor_options: Optional[dict] = None
    autoscaling_config: Optional[dict] = None
    max_concurrent_queries: int = 100
    # plain-data config delivered to the instance's reconfigure() — at
    # construction AND in place on redeploys that change only this field
    # (reference: serve deployment user_config lightweight updates)
    user_config: Optional[dict] = None

    def bind(self, *args, **kwargs) -> "Deployment":
        import dataclasses

        return dataclasses.replace(self, init_args=args, init_kwargs=kwargs)

    def options(self, **kw) -> "Deployment":
        import dataclasses

        return dataclasses.replace(self, **kw)


def deployment(_func_or_class=None, *, name: Optional[str] = None, **kwargs):
    """@serve.deployment decorator (reference: serve/api.py)."""

    def deco(target):
        return Deployment(
            func_or_class=target, name=name or target.__name__, **kwargs
        )

    if _func_or_class is not None:
        return deco(_func_or_class)
    return deco


def _get_or_create_controller():
    import ray_tpu
    from ray_tpu.serve.controller import ServeController

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        cls = ray_tpu.remote(ServeController)
        return cls.options(name=CONTROLLER_NAME, lifetime="detached", num_cpus=0).remote()


def run(deployment_obj: Deployment, *, _blocking: bool = False, http_port: Optional[int] = None):
    """Deploy (recursively: Deployment objects in init args become live
    handles — the deployment-graph compose of reference
    serve/_private/deployment_graph_build.py) and return a handle
    (reference: serve.run api.py:455)."""
    import ray_tpu
    from ray_tpu.serve.handle import DeploymentHandle

    controller = _get_or_create_controller()
    # resolve nested Deployment dependencies depth-first: each becomes a
    # DeploymentHandle passed to the parent's constructor
    def _resolve(v):
        if isinstance(v, Deployment):
            return run(v)
        return v

    deployment_obj = deployment_obj.options(
        init_args=tuple(_resolve(a) for a in deployment_obj.init_args),
        init_kwargs={k: _resolve(v) for k, v in deployment_obj.init_kwargs.items()},
    )
    # definition version computed HERE, where the original objects live —
    # the controller only sees deserialized copies, so identity comparison
    # there is meaningless (reference analog: deployment version strings)
    import hashlib

    import cloudpickle

    def_version = hashlib.sha1(
        cloudpickle.dumps(
            (
                deployment_obj.func_or_class,
                deployment_obj.init_args,
                deployment_obj.init_kwargs,
            )
        )
    ).hexdigest()
    ray_tpu.get(
        controller.deploy.remote(
            deployment_obj.name,
            deployment_obj.func_or_class,
            deployment_obj.init_args,
            deployment_obj.init_kwargs,
            deployment_obj.num_replicas,
            deployment_obj.ray_actor_options,
            deployment_obj.route_prefix,
            deployment_obj.autoscaling_config,
            deployment_obj.max_concurrent_queries,
            def_version,
            deployment_obj.user_config,
        ),
        timeout=300,
    )
    if http_port is not None:
        start_http_proxy(http_port)
    return DeploymentHandle(deployment_obj.name, controller)


def get_deployment_handle(name: str):
    from ray_tpu.serve.handle import DeploymentHandle

    return DeploymentHandle(name, _get_or_create_controller())


def list_deployments() -> Dict[str, dict]:
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.list_deployments.remote(), timeout=30)


def autoscale_tick():
    """Drive one autoscaling pass (tests/cron; the proxy actor also ticks)."""
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.autoscale_tick.remote(), timeout=60)


def delete(name: str):
    import ray_tpu

    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)


def shutdown():
    import ray_tpu

    for h in _proxy_handles.values():
        try:
            ray_tpu.kill(h)
        except Exception:
            pass
    _proxy_handles.clear()
    _proxy_urls.clear()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    for name in list(list_deployments()):
        delete(name)
    ray_tpu.kill(controller)


_STREAM_END = object()


def _overload_retry_after(exc) -> Optional[float]:
    """If ``exc`` is (or wraps) an overload-shaped error — the engine's
    EngineOverloadedError (replica-local admission queue full) or the
    handle's DeploymentBackpressureError (the WHOLE fleet saturated) —
    its suggested Retry-After in seconds; else None.  Replica-side
    raises reach the proxy wrapped in a RayTaskError whose pickled cause
    survives the hop."""
    from ray_tpu.exceptions import DeploymentBackpressureError, EngineOverloadedError

    seen = 0
    while exc is not None and seen < 8:
        if isinstance(exc, (EngineOverloadedError, DeploymentBackpressureError)):
            return max(0.0, float(getattr(exc, "retry_after_s", 1.0)))
        exc = getattr(exc, "cause", None) or exc.__cause__
        seen += 1
    return None


def _is_replica_local_reject(exc) -> bool:
    """True when ``exc`` wraps a SINGLE replica's rejection (overload or
    mid-drain) rather than fleet-wide saturation — the shape the proxy
    retries on the next-least-loaded replica before shedding 503."""
    from ray_tpu.exceptions import EngineOverloadedError, ReplicaDrainingError

    seen = 0
    while exc is not None and seen < 8:
        if isinstance(exc, (EngineOverloadedError, ReplicaDrainingError)):
            return True
        exc = getattr(exc, "cause", None) or exc.__cause__
        seen += 1
    return False


class HTTPProxy:
    """aiohttp ingress actor, one per node (reference:
    _private/http_proxy.py:189,333 — per-node proxies behind the cluster
    LB).  Its DeploymentHandles route local-first: replicas on the
    proxy's own node are preferred (handle.py _pick_replica).  Requests
    with ?stream=1 iterate a generator deployment and stream NDJSON."""

    def __init__(self, port: int):
        from concurrent.futures import ThreadPoolExecutor

        self.port = port
        self._handles = {}
        self.url = None
        # stream pulls park threads for the stream's lifetime: isolate
        # them from the default executor the non-stream path blocks on
        self._stream_executor = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="serve-stream"
        )

    async def start(self):
        import json

        from aiohttp import web

        import ray_tpu
        from ray_tpu.serve.handle import DeploymentHandle

        controller = _get_or_create_controller()

        async def handler(request):
            from ray_tpu.serve import tracing as serve_tracing

            # request record born at the ingress: serve_proxy_recv is the
            # TTFT/e2e origin (None when recording is off — every stamp
            # below gates on that)
            trace = serve_tracing.new_request()
            routes = ray_tpu.get(controller.routes.remote(), timeout=10)
            path = request.path
            name = None
            for prefix, dep_name in routes.items():
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    name = dep_name
                    break
            if name is None:
                return web.Response(status=404, text="no route")
            if trace is not None:
                trace["deployment"] = name
            if name not in self._handles:
                import asyncio as _aio

                # first touch of a deployment runs a sync SUBSCRIBE RPC in
                # the handle constructor: build it off-loop so the http
                # loop keeps serving (graftsan GS001).  setdefault keeps
                # the winner if two first requests race across the await;
                # the loser's subscription self-prunes via its weakref.
                h = await _aio.get_running_loop().run_in_executor(
                    None, DeploymentHandle, name, controller
                )
                self._handles.setdefault(name, h)
            handle = self._handles[name]
            handle.refresh_if_stale()
            try:
                body = await request.json()
            except Exception:
                body = (await request.read()).decode() or None
            import asyncio
            import functools

            if (
                request.query.get("stream") == "sse"
                or "text/event-stream" in request.headers.get("Accept", "")
            ):
                # continuous-batching engine deployments stream tokens as
                # Server-Sent Events: one `data:` frame per token batch,
                # first frame before generation completes (the dag-channel
                # token stream under handle.stream_tokens).  Admission
                # overload sheds BEFORE the stream opens: 503 +
                # Retry-After, the bounded failure mode.
                from ray_tpu.exceptions import EngineStreamError

                loop = asyncio.get_running_loop()
                it = handle.stream_tokens(body)

                def _next():
                    try:
                        return next(it)
                    except StopIteration:
                        return _STREAM_END

                try:
                    first = await loop.run_in_executor(self._stream_executor, _next)
                except Exception as e:  # noqa: BLE001 -- status line not sent yet: map to HTTP
                    retry = _overload_retry_after(e)
                    if retry is not None:
                        return web.Response(
                            status=503,
                            headers={"Retry-After": str(max(1, int(retry)))},
                            text="engine admission queue full",
                        )
                    return web.Response(status=500, text=f"stream failed: {e}")
                resp = web.StreamResponse(
                    headers={
                        "Content-Type": "text/event-stream",
                        "Cache-Control": "no-cache",
                    }
                )
                await resp.prepare(request)
                try:
                    chunk = first
                    while chunk is not _STREAM_END:
                        await resp.write(
                            (f"data: {json.dumps({'t': chunk})}\n\n").encode()
                        )
                        chunk = await loop.run_in_executor(
                            self._stream_executor, _next
                        )
                    await resp.write(b"event: done\ndata: {}\n\n")
                except Exception as e:  # noqa: BLE001 -- headers sent: the error travels as a typed SSE event
                    kind = (
                        "stream_error"
                        if isinstance(e, EngineStreamError)
                        else type(e).__name__
                    )
                    try:
                        await resp.write(
                            (
                                "event: error\ndata: "
                                + json.dumps({"error": str(e), "type": kind})
                                + "\n\n"
                            ).encode()
                        )
                    except Exception:
                        pass
                    it.close()
                try:
                    await resp.write_eof()
                except Exception:  # noqa: BLE001 -- client hung up mid-stream; nothing left to send
                    pass
                return resp

            if request.query.get("stream") == "1":
                # generator deployments stream over HTTP as NDJSON lines
                # (reference: serve StreamingResponse through the proxy);
                # pulls run on a DEDICATED executor so parked slow streams
                # can't starve the default pool the non-stream gets use
                resp = web.StreamResponse(
                    headers={"Content-Type": "application/x-ndjson"}
                )
                await resp.prepare(request)
                loop = asyncio.get_running_loop()
                it = handle.stream(body)

                def _next():
                    try:
                        return next(it)
                    except StopIteration:
                        return _STREAM_END

                try:
                    while True:
                        chunk = await loop.run_in_executor(
                            self._stream_executor, _next
                        )
                        if chunk is _STREAM_END:
                            break
                        await resp.write(
                            (json.dumps(chunk, default=str) + "\n").encode()
                        )
                except Exception as e:  # noqa: BLE001 — headers already sent
                    # mid-stream failure: the status line is gone, so the
                    # error travels as a final NDJSON line
                    try:
                        await resp.write(
                            (json.dumps({"error": str(e)}) + "\n").encode()
                        )
                    except Exception:
                        pass
                    it.close()
                await resp.write_eof()
                return resp

            from ray_tpu.exceptions import DeploymentBackpressureError

            loop = asyncio.get_running_loop()
            result = None
            last_exc = None
            # a single replica's rejection (overload / mid-drain) retries
            # on the next-least-loaded replica before shedding — 503 only
            # when the WHOLE fleet is saturated (serve/FLEET.md)
            for _attempt in range(3):
                try:
                    if trace is not None:
                        ref = handle.remote(body, _serve_trace=trace)
                    else:
                        ref = handle.remote(body)
                except DeploymentBackpressureError as e:
                    # nothing routable anywhere: shed now
                    return web.Response(
                        status=503,
                        headers={"Retry-After": str(max(1, int(e.retry_after_s)))},
                        text="deployment saturated",
                    )
                try:
                    result = await loop.run_in_executor(
                        None, functools.partial(ray_tpu.get, ref, timeout=120)
                    )
                    last_exc = None
                    break
                except Exception as e:  # noqa: BLE001 -- overload maps to 503, the rest re-raises
                    if not _is_replica_local_reject(e):
                        raise
                    last_exc = e
            if last_exc is not None:
                # every attempt hit a saturated/draining replica: bounded
                # rejection instead of unbounded queueing — clients back
                # off per Retry-After
                retry = _overload_retry_after(last_exc) or 1.0
                return web.Response(
                    status=503,
                    headers={"Retry-After": str(max(1, int(retry)))},
                    text="engine admission queue full",
                )
            if isinstance(result, (dict, list, str, int, float, bool)) or result is None:
                return web.json_response({"result": result})
            return web.Response(body=str(result).encode())

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", self.port)
        await site.start()
        actual = site._server.sockets[0].getsockname()[1]
        self.url = f"http://127.0.0.1:{actual}"
        return self.url

    async def ping(self):
        return "ok"


_proxy_handles: Dict[str, Any] = {}
_proxy_urls: Dict[str, str] = {}


def start_http_proxy(port: int = 8000) -> str:
    """Start HTTP ingress: one proxy actor PER ALIVE NODE, each pinned by
    node affinity and routing to its own node's replicas first (reference:
    _private/http_proxy.py — per-node proxies).  The driver's node binds
    ``port``; other nodes bind an ephemeral port (this runtime's test
    clusters share one host, where a fixed port would collide).  Returns
    the driver-node proxy's URL; all of them via proxy_addresses()."""
    import ray_tpu
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    my_node = bytes(worker_mod._require_connected().node_id).hex()
    alive = {n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]}
    # reconcile the cached set against the CURRENT cluster: drop proxies
    # on dead nodes (or from a previous cluster in this process — tests
    # init/shutdown repeatedly), add proxies for newly-joined nodes
    for nid in list(_proxy_handles):
        stale = nid not in alive
        if not stale:
            try:
                ray_tpu.get(_proxy_handles[nid].ping.remote(), timeout=10)
            except Exception:
                stale = True
        if stale:
            try:
                ray_tpu.kill(_proxy_handles[nid])
            except Exception:
                pass
            _proxy_handles.pop(nid, None)
            _proxy_urls.pop(nid, None)
    cls = ray_tpu.remote(HTTPProxy)
    started = []
    for nid in alive:
        if nid in _proxy_handles:
            continue
        h = cls.options(
            num_cpus=0,
            name=f"_serve_http_proxy::{nid}",
            scheduling_strategy=NodeAffinitySchedulingStrategy(nid),
        ).remote(port if nid == my_node else 0)
        _proxy_handles[nid] = h
        started.append(nid)
    for nid in started:
        _proxy_urls[nid] = ray_tpu.get(_proxy_handles[nid].start.remote(), timeout=120)
    return _proxy_urls.get(my_node) or next(iter(_proxy_urls.values()))


def proxy_addresses() -> Dict[str, str]:
    """node id (hex) → that node's proxy URL."""
    return dict(_proxy_urls)
