"""DeploymentHandle: client-side router to replicas.

Analog of the reference's handle/router pair (reference:
python/ray/serve/handle.py:225 RayServeHandle.remote →
_private/router.py:221 ReplicaSet.assign_replica — round-robin with an
in-flight cap per replica; config fan-out via LongPollClient,
_private/long_poll.py:67).  Two r2-weak fixes live here:

- in-flight accounting resolves on the core worker's io loop via
  on_object_done (no thread per request);
- replica membership is PUSH-invalidated: the controller publishes on the
  ``serve:<deployment>`` pubsub channel at every version bump, the handle
  marks itself stale and re-pulls on the next request — long-poll
  semantics without a poll loop.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller):
        self._name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._max_inflight = 100
        self._version = -1
        self._rr = itertools.count()
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._stale = threading.Event()
        self._refresh()
        self._subscribe_updates()

    def _subscribe_updates(self):
        """Controller pushes version bumps; the callback only flips a flag
        (it runs on the io thread and must not block).  Held via weakref so
        discarded handles don't accumulate in the worker's subscription
        list forever — a dead handle's callback prunes itself on the next
        publish."""
        import weakref

        from ray_tpu._private import worker as worker_mod

        try:
            cw = worker_mod._require_connected()
        except Exception:
            return  # pull path still works, just without push invalidation
        wself = weakref.ref(self)
        channel = f"serve:{self._name}"

        def _cb(_msg):
            h = wself()
            if h is None:
                subs = cw._subscriptions.get(channel, [])
                if _cb in subs:
                    subs.remove(_cb)
                return
            h._stale.set()

        try:
            cw.subscribe(channel, _cb)
        except Exception:
            pass

    def _refresh(self):
        import ray_tpu

        info = ray_tpu.get(self._controller.get_handles.remote(self._name), timeout=30)
        if info is None:
            raise ValueError(f"no deployment named {self._name!r}")
        with self._lock:
            self._replicas = info["replicas"]
            self._max_inflight = info["max_concurrent_queries"]
            self._version = info["version"]
            self._inflight = {}
        self._stale.clear()

    def _pick_replica(self):
        if self._stale.is_set():
            try:
                self._refresh()  # clears _stale on success
            except Exception:
                pass  # stale stays set: the NEXT request retries
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(f"deployment {self._name} has no replicas")
            # round-robin, skipping replicas at their in-flight cap
            for _ in range(n):
                idx = next(self._rr) % n
                if self._inflight.get(idx, 0) < self._max_inflight:
                    self._inflight[idx] = self._inflight.get(idx, 0) + 1
                    return idx, self._replicas[idx]
            # all saturated: take the round-robin pick anyway (backpressure
            # belongs to the replica's queue)
            idx = next(self._rr) % n
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            return idx, self._replicas[idx]

    def _release(self, idx: int):
        with self._lock:
            self._inflight[idx] = max(0, self._inflight.get(idx, 1) - 1)

    def remote(self, *args, **kwargs):
        """Async submit; returns an ObjectRef."""
        return self.method("__call__").remote(*args, **kwargs)

    def method(self, method_name: str):
        handle = self

        class _Method:
            def remote(self, *args, **kwargs):
                from ray_tpu._private import worker as worker_mod

                idx, replica = handle._pick_replica()
                ref = replica.handle_request.remote(method_name, args, kwargs)
                # decrement when the result resolves — an io-loop callback,
                # NOT a thread per request (r2 weak #6)
                try:
                    cw = worker_mod._require_connected()
                    cw.on_object_done(ref, lambda: handle._release(idx))
                except Exception:
                    handle._release(idx)  # fail open: don't wedge the cap
                return ref

        return _Method()

    def refresh_if_stale(self):
        """Kept for API compatibility; push invalidation makes explicit
        calls unnecessary."""
        if self._stale.is_set():
            try:
                self._refresh()
            except Exception:
                pass
