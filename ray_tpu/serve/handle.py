"""DeploymentHandle: client-side router to replicas.

Analog of the reference's handle/router pair (reference:
python/ray/serve/handle.py:225 RayServeHandle.remote →
_private/router.py:221 ReplicaSet.assign_replica; config fan-out via
LongPollClient, _private/long_poll.py:67).  Fleet behaviors live here
(serve/FLEET.md):

- in-flight accounting resolves on the core worker's io loop via
  on_object_done (no thread per request);
- replica membership is PUSH-invalidated: the controller publishes on the
  ``serve:<deployment>`` pubsub channel at every version bump, the handle
  marks itself stale and re-pulls on the next request — long-poll
  semantics without a poll loop.  Load snapshots piggyback on the same
  channel and are absorbed WITHOUT a re-pull;
- routing is power-of-two-choices least-pressure over local in-flight +
  fleet-reported queue depth and KV-page pressure, locality as tiebreak;
- all replicas saturated raises a typed ``DeploymentBackpressureError``
  (the proxy maps it to 503 + Retry-After) instead of over-admitting;
- ``stream_tokens`` fails over mid-stream: a dead replica's stream is
  resubmitted to a survivor and resumed from the delivered-token
  frontier with duplicates suppressed (greedy decoding makes the replay
  bit-identical), so clients see a latency blip, not an error.
"""

from __future__ import annotations

import itertools
import random
import threading
from typing import Any, Dict, List

_FAILOVER_ATTEMPTS = 3  # replica deaths one stream absorbs before erroring

# process-wide failover counter: handles live in driver/proxy processes,
# so the series merges with the controller's zero-init of the family
_failovers_counter = None
_failovers_lock = threading.Lock()


def _count_failover(deployment: str):
    global _failovers_counter
    try:
        with _failovers_lock:
            if _failovers_counter is None:
                from ray_tpu.util import metrics as metrics_mod

                _failovers_counter = metrics_mod.Counter(
                    "ray_tpu_serve_fleet_failovers_total",
                    description="mid-stream replica failovers (handle resubmits)",
                    tag_keys=("deployment",),
                )
        _failovers_counter.inc(1.0, tags={"deployment": deployment})
    except Exception:
        pass  # metrics plane down: the failover itself still happened


def _fleet_event(message: str, **fields):
    """source=serve_fleet timeline event, fire-and-forget (failover is a
    data-path action — bookkeeping must not add a blocking head RPC)."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.protocol import MsgType

    try:
        cw = worker_mod._require_connected()
    except Exception:
        return
    payload = {
        "severity": "WARNING",
        "source": "serve_fleet",
        "message": message,
        "fields": fields,
    }

    async def _send():
        try:
            await cw.conn.send(MsgType.RECORD_EVENT, payload)
        except (ConnectionError, OSError):
            pass

    try:
        cw.io.spawn(_send())
    except Exception:  # graftlint: disable=silent-except -- event bookkeeping is best-effort; the failover already landed
        pass


def _unwrap_cause(exc, types, limit: int = 8):
    """First exception of `types` on the cause chain (RayTaskError keeps
    the remote exception under .cause; __cause__ covers local re-raises).
    Same walk the proxy uses for Retry-After extraction."""
    e, seen = exc, 0
    while e is not None and seen < limit:
        if isinstance(e, types):
            return e
        e = getattr(e, "cause", None) or getattr(e, "__cause__", None)
        seen += 1
    return None


def _rebuild_handle(name: str) -> "DeploymentHandle":
    from ray_tpu.serve.api import get_deployment_handle

    return get_deployment_handle(name)


class DeploymentHandle:
    # push is the fast path; this pull interval is the self-heal fallback
    # for a missed publish (failed subscribe, dropped PUBLISH RPC)
    PULL_FALLBACK_S = 5.0

    def __init__(self, deployment_name: str, controller):
        self._name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._replica_nodes: List[str] = []
        self._replica_names: List[str] = []
        # replica name -> load snapshot; REPLACED whole (never mutated) by
        # the pubsub callback, so readers need no lock
        self._loads: Dict[str, dict] = {}
        self._my_node = self._resolve_my_node()
        self._max_inflight = 100
        self._version = -1
        self._rr = itertools.count()
        self._rng = random.Random()
        # keyed by replica actor id (NOT slot index): releases after a
        # membership change must decrement the replica that actually served
        self._inflight: Dict[Any, int] = {}
        self._lock = threading.Lock()
        self._stale = threading.Event()
        self._last_refresh = 0.0
        self._last_refresh_attempt = 0.0
        # LAZY first refresh: a handle may deserialize inside the
        # controller itself (deployment-graph args) — an eager get_handles
        # RPC there would be the controller calling its own busy self
        self._stale.set()
        self._subscribe_updates()

    def _subscribe_updates(self):
        """Controller pushes version bumps; the callback only flips a flag
        (it runs on the io thread and must not block).  Held via weakref so
        discarded handles don't accumulate in the worker's subscription
        list forever — a dead handle's callback prunes itself on the next
        publish."""
        import weakref

        from ray_tpu._private import worker as worker_mod

        try:
            cw = worker_mod._require_connected()
        except Exception:
            return  # pull path still works, just without push invalidation
        wself = weakref.ref(self)
        channel = f"serve:{self._name}"

        def _cb(_msg):
            h = wself()
            if h is None:
                subs = cw._subscriptions.get(channel, [])
                if _cb in subs:
                    subs.remove(_cb)
                return
            if isinstance(_msg, dict):
                # load snapshots piggyback on every publish (controller
                # poller, ~1 Hz): absorb them here — dict REPLACEMENT, io
                # thread never blocks — and only force a membership
                # re-pull when the version actually moved; a load-only
                # publish must not turn push-invalidation into 1 Hz
                # controller RPCs per handle
                loads = _msg.get("loads")
                if isinstance(loads, dict):
                    h._loads = dict(loads)
                if _msg.get("version", -2) == h._version:
                    return
            h._stale.set()

        try:
            cw.subscribe(channel, _cb)
        except Exception:
            pass

    def _refresh(self):
        import time as _time

        import ray_tpu

        info = ray_tpu.get(self._controller.get_handles.remote(self._name), timeout=30)
        if info is None:
            raise ValueError(f"no deployment named {self._name!r}")
        with self._lock:
            # identity-keyed counters survive membership changes untouched;
            # drop entries for replicas that left the set
            self._replicas = info["replicas"]
            self._replica_nodes = info.get("replica_nodes") or [""] * len(
                self._replicas
            )
            self._replica_names = info.get("replica_names") or [""] * len(
                self._replicas
            )
            if isinstance(info.get("replica_loads"), dict):
                self._loads = dict(info["replica_loads"])
            self._max_inflight = info["max_concurrent_queries"]
            self._version = info["version"]
            live = {self._rid(r) for r in self._replicas}
            self._inflight = {
                k: v for k, v in self._inflight.items() if k in live
            }
        self._last_refresh = _time.monotonic()
        self._stale.clear()

    @staticmethod
    def _rid(replica):
        return getattr(replica, "_actor_id", id(replica))

    @staticmethod
    def _resolve_my_node() -> str:
        import os

        nid = os.environ.get("RAY_TPU_NODE_ID", "")
        if nid:
            return nid
        try:
            from ray_tpu._private import worker as worker_mod

            return bytes(worker_mod._require_connected().node_id).hex()
        except Exception:
            return ""

    def _pressure(self, idx: int) -> float:
        """Routing pressure for replica slot ``idx``: what THIS handle has
        in flight there, plus the fleet-reported queue depth and KV-page
        pressure from the controller's piggybacked load snapshots.
        max(local, reported-inflight) because the report already counts
        our own in-flight work — summing would double-charge it."""
        rid = self._rid(self._replicas[idx])
        local = float(self._inflight.get(rid, 0))
        ld = {}
        if idx < len(self._replica_names):
            ld = self._loads.get(self._replica_names[idx]) or {}
        reported = float(ld.get("inflight", 0.0) or 0.0)
        queue = float(ld.get("queue_depth", 0.0) or 0.0)
        page_frac = float(ld.get("kv_page_frac", 0.0) or 0.0)
        # page pressure scales by the admission cap so a nearly-full KV
        # pool weighs like a nearly-full queue, not like one request
        return max(local, reported) + queue + page_frac * self._max_inflight

    def _pick_replica(self, exclude=frozenset()):
        """Least-pressure routing with power-of-two-choices: sample two
        eligible replicas, take the lower pressure, locality breaking
        ties (tiebreak, NOT filter — a saturated local replica loses to
        an idle remote one).  Eligible = under this handle's in-flight
        cap, not reported draining, not in ``exclude`` (the failover
        loop's dead-replica set).  Nothing eligible raises a typed
        ``DeploymentBackpressureError`` — the cap is a real bound, not a
        suggestion; the proxy maps it to 503 + Retry-After."""
        import time as _time

        from ray_tpu.exceptions import DeploymentBackpressureError

        now = _time.monotonic()
        need = self._stale.is_set() or now - self._last_refresh > self.PULL_FALLBACK_S
        # attempt backoff: a dead controller must not add a blocking RPC to
        # every request while the stale flag is stuck set
        if need and now - self._last_refresh_attempt > 1.0:
            self._last_refresh_attempt = now
            try:
                self._refresh()  # clears _stale on success
            except Exception:
                pass  # a later request (post-backoff) retries
        with self._lock:
            n = len(self._replicas)
        if n == 0 and self._last_refresh == 0:
            # lazy handle that never managed a refresh: one blocking
            # attempt so the caller sees the real error (unknown name /
            # controller down) — still backoff-gated so a dead controller
            # can't add a long RPC to every request
            if _time.monotonic() - self._last_refresh_attempt > 1.0:
                self._last_refresh_attempt = _time.monotonic()
                self._refresh()
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(f"deployment {self._name} has no replicas")
            loads = self._loads  # replacement-dict snapshot
            cands = []
            for i in range(n):
                rid = self._rid(self._replicas[i])
                if rid in exclude:
                    continue
                if self._inflight.get(rid, 0) >= self._max_inflight:
                    continue
                rn = self._replica_names[i] if i < len(self._replica_names) else ""
                if (loads.get(rn) or {}).get("draining"):
                    continue  # mid-drain: admits nothing new
                cands.append(i)
            if not cands:
                raise DeploymentBackpressureError(
                    f"deployment {self._name}: all {n} replicas saturated "
                    f"(cap {self._max_inflight})",
                    retry_after_s=1.0,
                )
            if len(cands) > 2:
                cands = self._rng.sample(cands, 2)
            local_n = len(self._replica_nodes)

            def _key(i):
                is_remote = 1
                if self._my_node and i < local_n:
                    is_remote = 0 if self._replica_nodes[i] == self._my_node else 1
                return (self._pressure(i), is_remote, i)

            idx = min(cands, key=_key)
            rid = self._rid(self._replicas[idx])
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
            return rid, self._replicas[idx]

    def _release(self, rid):
        with self._lock:
            if rid in self._inflight:
                self._inflight[rid] = max(0, self._inflight[rid] - 1)

    def remote(self, *args, **kwargs):
        """Async submit; returns an ObjectRef."""
        return self.method("__call__").remote(*args, **kwargs)

    def stream(self, *args, **kwargs):
        """Call a GENERATOR deployment method and iterate its chunks as
        they are produced (reference: serve streaming responses).  The
        stream is pinned to ONE replica (the generator lives there);
        chunks are pulled in batches through the normal actor-call path.

        for token in handle.stream(prompt): ...
        """
        return self.method("__call__").stream(*args, **kwargs)

    def stream_tokens(
        self,
        prompt,
        *,
        max_new_tokens=None,
        eos_token=None,
        timeout: float = 600.0,
    ):
        """Stream token frames from a continuous-batching engine
        deployment (serve/engine/): yields lists of token ids AS THE
        ENGINE PRODUCES THEM — the first frame lands after the prompt's
        final prefill chunk, long before the sequence completes.

        Transport: one ``engine_stream_start`` actor call, then frames
        ride a dag channel straight from the replica (shm ring when
        co-located — no per-token RPC, no head hop).  Falls back to
        pulling the stream's outbox over the normal actor-call path when
        the direct transport is unavailable (client mode, feature off).

        Mid-stream replica death FAILS OVER (serve/FLEET.md): the
        ORIGINAL request is resubmitted to a surviving replica and the
        first ``delivered`` tokens of the replay are suppressed — greedy
        decoding over identical weights makes them bit-identical, so the
        resumed stream continues exactly where the dead one stopped.  A
        replica-local overload or drain rejection retries the
        next-least-loaded sibling without counting as a failover.  Only
        when no survivor remains does the typed error (``EngineStream
        Error`` / ``DeploymentBackpressureError``) reach the caller."""
        from ray_tpu.exceptions import (
            DeploymentBackpressureError,
            EngineOverloadedError,
            EngineStreamError,
            RayActorError,
            ReplicaDrainingError,
            WorkerCrashedError,
        )

        delivered = 0
        excluded = set()
        failovers = 0
        last_err = None
        while True:
            try:
                rid, replica = self._pick_replica(exclude=frozenset(excluded))
            except DeploymentBackpressureError:
                if last_err is not None:
                    raise last_err  # survivors exhausted: the stream death wins
                raise
            try:
                skip = delivered
                for frame in self._stream_once(
                    replica, prompt, max_new_tokens, eos_token, timeout
                ):
                    if skip:
                        # resumed stream: drop the already-delivered
                        # prefix (bit-identical replay under greedy)
                        if skip >= len(frame):
                            skip -= len(frame)
                            continue
                        frame = frame[skip:]
                        skip = 0
                    delivered += len(frame)
                    yield frame
                return
            except GeneratorExit:
                raise  # consumer walked away: no retry on its behalf
            except Exception as e:
                retriable = _unwrap_cause(
                    e, (EngineOverloadedError, ReplicaDrainingError)
                )
                if retriable is not None and delivered == 0:
                    # admission-time rejection: try the next-least-loaded
                    # sibling before shedding — a single replica's
                    # overload is a routing miss, not a fleet 503
                    excluded.add(rid)
                    last_err = e
                    continue
                # WorkerCrashedError: the kill landed while the replica
                # was still executing the submission call itself — same
                # death, earlier phase, same failover
                dead = _unwrap_cause(
                    e,
                    (
                        EngineStreamError,
                        RayActorError,
                        WorkerCrashedError,
                        ConnectionError,
                    ),
                )
                if dead is None or failovers >= _FAILOVER_ATTEMPTS:
                    raise
                failovers += 1
                excluded.add(rid)
                last_err = e
                self._stale.set()  # membership likely changed: re-pull
                _count_failover(self._name)
                _fleet_event(
                    f"serve fleet failover: {self._name} stream resumed at "
                    f"token {delivered}",
                    deployment=self._name,
                    delivered=delivered,
                    attempt=failovers,
                    error=type(dead).__name__,
                )
            finally:
                self._release(rid)

    def _stream_once(self, replica, prompt, max_new_tokens, eos_token, timeout):
        """One streaming attempt against ONE replica; yields token-id
        lists.  Replica death surfaces as a raised typed error — the
        failover loop in stream_tokens owns retries and accounting."""
        import ray_tpu
        from ray_tpu.exceptions import EngineStreamError
        from ray_tpu.serve import tracing as serve_tracing
        from ray_tpu.serve.engine import transport as engine_transport

        trace = serve_tracing.new_request(self._name)
        serve_tracing.stamp(trace, "serve_route")
        kwargs = {"max_new_tokens": max_new_tokens, "eos_token": eos_token}
        if trace is not None:
            kwargs["_serve_trace"] = trace
        start = ray_tpu.get(
            replica.handle_request.remote("engine_stream_start", (prompt,), kwargs),
            timeout=600,
        )
        try:
            ts = engine_transport.open_token_stream(replica, start, timeout=timeout)
        except EngineStreamError:
            ts = None  # no direct transport here: pull path below
        if ts is not None:
            yield from ts
            return
        sid = start["sid"]
        finished = False
        try:
            while True:
                frames, done = ray_tpu.get(
                    replica.handle_request.remote("engine_stream_next", (sid,), {}),
                    timeout=timeout,
                )
                for f in frames:
                    if f.get("error"):
                        finished = True
                        raise EngineStreamError(str(f["error"]))
                    if f.get("t"):
                        yield list(f["t"])
                    if f.get("done"):
                        finished = True
                if finished or done:
                    return
        finally:
            if not finished:
                # abandoned mid-stream: free the replica-side request
                try:
                    replica.handle_request.remote("engine_stream_cancel", (sid,), {})
                except Exception:
                    pass

    def method(self, method_name: str):
        handle = self

        class _Method:
            def remote(self, *args, **kwargs):
                from ray_tpu._private import worker as worker_mod
                from ray_tpu.serve import tracing as serve_tracing

                # serve request tracing: adopt the ingress's record (the
                # HTTP proxy passes one) or mint one here for bare-handle
                # callers; the replica pops the reserved kwarg before the
                # user callable ever sees kwargs.  With recording off the
                # trace is None and nothing is attached (one flag check).
                trace = kwargs.pop("_serve_trace", None)
                if trace is None:
                    trace = serve_tracing.new_request(handle._name)
                elif not trace.get("deployment"):
                    trace["deployment"] = handle._name
                idx, replica = handle._pick_replica()
                serve_tracing.stamp(trace, "serve_route")
                if trace is not None:
                    kwargs = {**kwargs, "_serve_trace": trace}
                ref = replica.handle_request.remote(method_name, args, kwargs)
                # decrement when the result resolves — an io-loop callback,
                # NOT a thread per request (r2 weak #6)
                try:
                    cw = worker_mod._require_connected()
                    cw.on_object_done(ref, lambda: handle._release(idx))
                except Exception:
                    handle._release(idx)  # fail open: don't wedge the cap
                return ref

            def stream(self, *args, **kwargs):
                import ray_tpu

                idx, replica = handle._pick_replica()
                sid = None
                finished = False
                try:
                    sid = ray_tpu.get(
                        replica.handle_stream_start.remote(method_name, args, kwargs),
                        timeout=600,
                    )
                    # adaptive batch: first pull returns on the FIRST chunk
                    # (token latency), later pulls grow toward 16 so fast
                    # generators aren't RPC-bound per item
                    batch = 1
                    while True:
                        chunks, stream_done = ray_tpu.get(
                            replica.handle_stream_next.remote(sid, batch),
                            timeout=600,
                        )
                        batch = min(batch * 2, 16)
                        for c in chunks:
                            yield c
                        if stream_done:
                            finished = True
                            return
                finally:
                    if sid is not None and not finished:
                        # abandoned mid-stream (break / timeout): release
                        # the replica-side generator + inflight slot
                        try:
                            replica.handle_stream_cancel.remote(sid)
                        except Exception:
                            pass
                    handle._release(idx)

        return _Method()

    def __reduce__(self):
        # handles cross process boundaries (deployment-graph composition
        # ships a dependency's handle into the parent replica's __init__):
        # rebuild fresh in the destination, resolving the controller there
        return (_rebuild_handle, (self._name,))

    def refresh_if_stale(self):
        """Refresh only when the push marked us stale — NO per-request
        controller RPC (that hop is what push-invalidation removes; missed
        pushes are healed by _pick_replica's PULL_FALLBACK_S timer)."""
        if self._stale.is_set():
            try:
                self._refresh()
            except Exception:
                pass
