"""DeploymentHandle: client-side router to replicas.

Analog of the reference's handle/router pair (reference:
python/ray/serve/handle.py:225 RayServeHandle.remote →
_private/router.py:221 ReplicaSet.assign_replica — round-robin with an
in-flight cap per replica; config updates via long poll :67).  We refresh
replica membership from the controller on a version poll instead of a
long-poll push (same effect at this scale).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller):
        self._name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._max_inflight = 100
        self._version = -1
        self._rr = itertools.count()
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._refresh()

    def _refresh(self):
        import ray_tpu

        info = ray_tpu.get(self._controller.get_handles.remote(self._name), timeout=30)
        if info is None:
            raise ValueError(f"no deployment named {self._name!r}")
        with self._lock:
            self._replicas = info["replicas"]
            self._max_inflight = info["max_concurrent_queries"]
            self._version = info["version"]

    def _pick_replica(self):
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(f"deployment {self._name} has no replicas")
            # round-robin, skipping replicas at their in-flight cap
            for _ in range(n):
                idx = next(self._rr) % n
                if self._inflight.get(idx, 0) < self._max_inflight:
                    self._inflight[idx] = self._inflight.get(idx, 0) + 1
                    return idx, self._replicas[idx]
            # all saturated: take the round-robin pick anyway (backpressure
            # belongs to the replica's queue)
            idx = next(self._rr) % n
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            return idx, self._replicas[idx]

    def remote(self, *args, **kwargs):
        """Async submit; returns an ObjectRef."""
        return self.method("__call__").remote(*args, **kwargs)

    def method(self, method_name: str):
        handle = self

        class _Method:
            def remote(self, *args, **kwargs):
                idx, replica = handle._pick_replica()
                ref = replica.handle_request.remote(method_name, args, kwargs)
                # decrement on resolution (best-effort, thread offload)
                def _done():
                    import ray_tpu

                    try:
                        ray_tpu.wait([ref], num_returns=1, timeout=300)
                    finally:
                        with handle._lock:
                            handle._inflight[idx] = max(0, handle._inflight.get(idx, 1) - 1)

                threading.Thread(target=_done, daemon=True).start()
                return ref

        return _Method()

    def refresh_if_stale(self):
        import ray_tpu

        try:
            info = ray_tpu.get(self._controller.get_handles.remote(self._name), timeout=10)
            if info and info["version"] != self._version:
                with self._lock:
                    self._replicas = info["replicas"]
                    self._version = info["version"]
        except Exception:
            pass
