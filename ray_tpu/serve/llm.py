"""Tensor-parallel sharded LLM serving engine.

BASELINE config #5 serves Llama-2-7B, and one 16G v5e cannot hold 7B in
bf16 (~13.5 GB weights before the KV cache) — so 7B serving is a MESH
story: weights AND the KV cache are sharded over a ``tp`` axis, the
per-token decode step is jitted once over the mesh with the cache buffers
donated (no double-buffered carry), and XLA inserts the attention/MLP
output-projection psums that ride ICI.  The reference never solves this
inside Serve — its replicas wrap user torch modules and model sharding
happens outside (reference: python/ray/serve/_private/replica.py:58);
here the sharded engine IS the replica's model, so a deployment scales
from one chip (tp=1) to a pod slice by changing one argument.

Sharding layout (megatron-style, from LlamaModel.param_pspecs):
  wq/wk/wv/w_gate/w_up : [L, E, out]  — out (heads / ffn) split over tp
  wo/w_down            : [L, in, E]   — in split over tp (psum after)
  tok_emb / out_head   : vocab split over tp (psum gather / sharded logits)
  KV cache             : [L, B, S, KV, D] — KV heads split over tp
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ray_tpu.models.llama import LlamaConfig, LlamaModel

__all__ = ["ShardedLLM", "llm_deployment", "engine_llm_deployment"]


def _resolve_cfg(model, max_seq_len):
    """LlamaConfig from a constructor name or an instance (worker-side —
    shared by the static and engine deployment factories)."""
    import dataclasses

    import jax.numpy as jnp

    if isinstance(model, LlamaConfig):
        return (
            model
            if max_seq_len is None
            else dataclasses.replace(model, max_seq_len=max_seq_len)
        )
    return getattr(LlamaConfig, model)(
        max_seq_len=max_seq_len or 256, param_dtype=jnp.bfloat16
    )


def _parse_prompt_spec(spec, vocab_size: int, default_new: int):
    """Normalize the three accepted request shapes into
    (prompt_ids, max_new_tokens, eos_token):

    - int seed       -> one-token prompt (the static path's wire shape)
    - [ids...]       -> explicit prompt
    - {"prompt": int|[ids...], "max_new_tokens": n, "eos_token": t}
    """
    max_new, eos = default_new, None
    if isinstance(spec, dict):
        max_new = int(spec.get("max_new_tokens") or default_new)
        eos = spec.get("eos_token")
        eos = None if eos is None else int(eos)
        spec = spec.get("prompt", 0)
    if isinstance(spec, (list, tuple)):
        ids = [int(t) % vocab_size for t in spec]
    else:
        ids = [int(spec) % vocab_size]
    return ids, max_new, eos


def _filter_spec(spec, axis_names):
    """Drop mesh axes the serving mesh doesn't have (e.g. the training
    pspecs name fsdp; a pure-tp serving mesh replicates those dims)."""
    from jax.sharding import PartitionSpec as P

    return P(*(a if a in axis_names else None for a in spec))


class ShardedLLM:
    """A llama-family model sharded over a 1-D tp mesh, ready to decode.

    init:
      "random" — normal(0, 0.02) weights (bench/serving without a ckpt)
      "cheap"  — deterministic iota-pattern fill (dryrun at 7B shape: no
                 7-billion-sample RNG on a 1-core host; still exercises
                 every collective with non-trivial values)
      dict     — a params pytree (or host arrays) to shard onto the mesh
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        devices: Optional[Sequence[Any]] = None,
        tp: Optional[int] = None,
        init: Any = "random",
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        devices = list(devices if devices is not None else jax.devices())
        tp = int(tp or len(devices))
        if tp > len(devices):
            raise ValueError(f"tp={tp} but only {len(devices)} devices")
        for dim, name in (
            (cfg.n_kv_heads, "n_kv_heads"),
            (cfg.hidden_dim, "hidden_dim"),
            (cfg.padded_vocab, "padded_vocab"),
            (cfg.dim, "dim"),
        ):
            if dim % tp:
                raise ValueError(f"{name}={dim} not divisible by tp={tp}")
        self.cfg = cfg
        self.tp = tp
        self.model = LlamaModel(cfg)
        self.mesh = Mesh(np.array(devices[:tp]), ("tp",))

        pspecs = self.model.param_pspecs()
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, _filter_spec(s, ("tp",))),
            pspecs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.cache_sharding = NamedSharding(self.mesh, P(None, None, None, "tp", None))
        self._repl = NamedSharding(self.mesh, P())

        shapes = jax.eval_shape(self.model.init, jax.random.PRNGKey(seed))
        if isinstance(init, dict):
            self.params = jax.tree.map(
                lambda x, sh: jax.device_put(x, sh), init, self.param_shardings
            )
        elif init == "cheap":
            # deterministic per-shard numpy fill via make_array_from_callback
            # — no XLA init program, no 2x cast transients; each device
            # writes only ITS shard.  Values vary over the last two dims
            # (broadcast over leading), which is non-degenerate enough to
            # exercise every collective with real data at 7B shape on a
            # 1-core dryrun host in tens of seconds.
            import zlib

            def fill(path, s, sharding):
                if "norm" in path:
                    return jax.make_array_from_callback(
                        s.shape,
                        sharding,
                        lambda idx: np.ones(
                            tuple(
                                len(range(*sl.indices(d)))
                                for sl, d in zip(idx, s.shape)
                            ),
                            s.dtype,
                        ),
                    )
                salt = zlib.crc32(path.encode())

                def cb(idx):
                    sl = [range(*x.indices(d)) for x, d in zip(idx, s.shape)]
                    shape = tuple(len(r) for r in sl)
                    j = np.arange(sl[-1].start, sl[-1].stop, dtype=np.int64)
                    col = ((j * 2654435761 + salt) % 1009) / 1009.0 - 0.5
                    if len(shape) >= 2:
                        i = np.arange(sl[-2].start, sl[-2].stop, dtype=np.int64)
                        row = ((i * 40503 + salt) % 997) / 997.0 - 0.5
                        mat = (col[None, :] + row[:, None]) * 0.02
                    else:
                        mat = col * 0.02
                    out = np.broadcast_to(mat, shape).astype(s.dtype)
                    return np.ascontiguousarray(out)

                return jax.make_array_from_callback(s.shape, sharding, cb)

            params = {}
            for k, v in shapes.items():
                if isinstance(v, dict):
                    params[k] = {
                        k2: fill(k2, s, self.param_shardings[k][k2])
                        for k2, s in v.items()
                    }
                else:
                    params[k] = fill(k, v, self.param_shardings[k])
            self.params = params
        elif init == "random":
            self.params = jax.jit(
                self.model.init, out_shardings=self.param_shardings
            )(jax.random.PRNGKey(seed))
        else:
            raise ValueError(f"unknown init {init!r}")

        model = self.model

        def prefill(params, cache, prompt_t):
            """Teacher-forced scan over prompt positions; returns the cache
            and the last position's logits.  prompt_t: [P, B, 1]."""

            def body(carry, xt):
                cache, _ = carry
                t, tok = xt
                logits, cache = model.decode_step(params, cache, tok, t)
                return (cache, logits), None

            P_len = prompt_t.shape[0]
            ts = jnp.arange(P_len)
            init_logits = jnp.zeros(
                (prompt_t.shape[1], cfg.padded_vocab), cfg.compute_dtype
            )
            (cache, logits), _ = jax.lax.scan(
                body, (cache, init_logits), (ts, prompt_t)
            )
            return cache, logits

        def generate_from(params, cache, logits, start_pos, n_new):
            """Greedy decode n_new tokens starting from prefill logits
            (n_new is static: the scan length is baked into the program)."""

            def body(carry, t):
                tok, cache = carry
                logits, cache = model.decode_step(params, cache, tok, t)
                nxt = jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
                return (nxt, cache), nxt[:, 0]

            first = jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
            if n_new == 1:
                return first, cache
            (_, cache), toks = jax.lax.scan(
                body, (first, cache), start_pos + jnp.arange(n_new - 1)
            )
            return jnp.concatenate([first.T, toks], axis=0).T, cache

        def full_generate(params, cache, prompt_t, n_new):
            cache, logits = prefill(params, cache, prompt_t)
            toks, cache = generate_from(
                params, cache, logits, prompt_t.shape[0], n_new
            )
            return toks

        # ONE compiled program per (B, P, n_new): prompt scan + decode scan
        # stay on-chip (per-token host dispatch would be RPC-bound over the
        # axon tunnel); the cache is created outside and DONATED so XLA
        # updates it in place instead of double-buffering the scan carry
        # (the r4 B=16 HBM cliff).
        self._generate = jax.jit(full_generate, static_argnums=(3,), donate_argnums=(1,))
        # split pair for the traced serving path: prefill and decode as
        # separate programs so the first token's logits are a HOST-VISIBLE
        # boundary — what TTFT/TPOT measure (and the baseline the
        # continuous-batching engine has to beat).  jit objects are lazy:
        # untraced callers (dryrun, bench fused path) never compile these.
        self._prefill = jax.jit(prefill, donate_argnums=(1,))
        self._decode = jax.jit(
            generate_from, static_argnums=(4,), donate_argnums=(1,)
        )
        self._init_cache = jax.jit(
            self.model.init_cache, static_argnums=(0,), out_shardings=self.cache_sharding
        )
        self._jnp = jnp

    # ------------------------------------------------------------------ api

    def generate(self, prompts: np.ndarray, n_new: int, stage_cb=None) -> np.ndarray:
        """prompts [B, P] int32 → generated tokens [B, n_new] (greedy).

        ``stage_cb(phase)`` opts into the SPLIT prefill/decode pair so the
        first token is a host-visible boundary: the callback fires with
        ``serve_prefill_start`` / ``serve_first_token`` /
        ``serve_decode_end`` (canonical task_events names — the serve
        tracer's stamp_batch slots straight in).  Without it the fused
        one-program path runs unchanged."""
        import jax

        jnp = self._jnp
        prompts = np.asarray(prompts, np.int32)
        B, P_len = prompts.shape
        if P_len + n_new > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt {P_len} + new {n_new} exceeds max_seq_len {self.cfg.max_seq_len}"
            )
        cache = self._init_cache(B)
        prompt_t = jnp.asarray(prompts.T[:, :, None])  # [P, B, 1]
        if stage_cb is None:
            toks = self._generate(self.params, cache, prompt_t, int(n_new))
            return np.asarray(toks)
        stage_cb("serve_prefill_start")
        cache, logits = self._prefill(self.params, cache, prompt_t)
        # first token's logits resident on host clock = TTFT endpoint
        jax.block_until_ready(logits)
        stage_cb("serve_first_token")
        toks, cache = self._decode(self.params, cache, logits, P_len, int(n_new))
        toks = np.asarray(toks)  # device→host sync: decode truly done
        stage_cb("serve_decode_end")
        return toks

    def engine_programs(self, *, num_pages: int, page_size: int) -> Dict[str, Any]:
        """The continuous-batching engine's three jitted programs over
        THIS mesh: page-pool init, prefill chunk, decode step
        (models/llama.py paged variants).  The pool is sharded like the
        contiguous cache (KV heads over tp) and DONATED into every call,
        so the engine's resident loop re-uses one in-place buffer per
        program — and because the paged programs are shaped by pool
        geometry only, the whole mixed-length fleet shares exactly one
        compiled decode shape (the engine asserts this via
        ``compile_stats``)."""
        import functools

        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        page_sharding = NamedSharding(self.mesh, P(None, None, None, "tp", None))
        repl = NamedSharding(self.mesh, P())
        # explicit out_shardings keep the pool's NamedSharding STABLE
        # across calls: without them the first program's output drops to
        # an inferred sharding, which flips the next call's jit cache key
        # — one silent recompile per program, exactly what the engine's
        # no-recompilation contract forbids
        step_out = (repl, (page_sharding, page_sharding))
        return {
            "init": jax.jit(
                functools.partial(self.model.init_pages, num_pages, page_size),
                out_shardings=(page_sharding, page_sharding),
            ),
            "prefill": jax.jit(
                functools.partial(self.model.prefill_chunk_paged, page_size=page_size),
                donate_argnums=(1,),
                out_shardings=step_out,
            ),
            "decode": jax.jit(
                functools.partial(self.model.decode_step_paged, page_size=page_size),
                donate_argnums=(1,),
                out_shardings=step_out,
            ),
        }

    def param_count(self) -> int:
        import jax

        return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self.params))

    def shard_stats(self) -> Dict[str, Any]:
        """Total param bytes and per-device resident bytes — the evidence
        that the model actually lives 1/tp per chip."""
        import jax

        total = 0
        per_device: Dict[str, int] = {}
        for leaf in jax.tree_util.tree_leaves(self.params):
            total += leaf.nbytes
            for sh in leaf.addressable_shards:
                key = str(sh.device)
                per_device[key] = per_device.get(key, 0) + sh.data.nbytes
        return {"total_bytes": total, "per_device_bytes": per_device}


def llm_deployment(
    model="llama_3b",
    *,
    max_seq_len: Optional[int] = None,
    new_tokens: int = 32,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.02,
    num_tpus: int = 1,
    tp: Optional[int] = None,
    autoscaling_config: Optional[dict] = None,
    prompt_pad: Optional[int] = None,
):
    """Build a Serve deployment wrapping a ShardedLLM replica.

    ``model`` is a LlamaConfig constructor name ("llama_3b", "llama2_7b",
    ...) or a LlamaConfig INSTANCE (resolved worker-side either way —
    pass an instance for configs the name registry doesn't have).  The
    replica claims ``num_tpus`` chips and shards over every device jax
    exposes inside the actor (tp defaults to all of them) — the same code
    path serves llama_3b on one chip and llama2_7b on a mesh."""
    from ray_tpu import serve

    @serve.deployment(
        name="llm",
        ray_actor_options={"num_tpus": num_tpus},
        max_concurrent_queries=64,
        autoscaling_config=autoscaling_config
        or {
            "min_replicas": 1,
            "max_replicas": 1,
            "target_num_ongoing_requests_per_replica": 32,
        },
    )
    class LLMDeployment:
        def __init__(self):
            import jax

            # an explicit max_seq_len overrides; otherwise the instance's
            # own value stands
            cfg = _resolve_cfg(model, max_seq_len)
            self.engine = ShardedLLM(cfg, tp=tp)
            self.platform = jax.devices()[0].platform

        @serve.batch(
            max_batch_size=max_batch_size, batch_wait_timeout_s=batch_wait_timeout_s
        )
        async def generate(self, prompts):
            from ray_tpu.serve import tracing as serve_tracing

            # run the EXACT batch — padding partial batches to
            # max_batch_size with [[0]] rows decoded the padding at full
            # cost (in a fixed-shape XLA program a "masked" row still buys
            # every FLOP, so honesty means a smaller program, not a mask).
            # The compile cache grows one program per distinct partial
            # size, bounded by max_batch_size; steady-state traffic rides
            # the full-batch program it always compiled anyway.
            #
            # Multi-token prompts (the mixed-length bench's wire shape)
            # pad to the LONGEST prompt in the coalesced batch (or the
            # fixed ``prompt_pad``, which also pins the compile shape) —
            # whole-request batching's intrinsic cost: every short row
            # pays the longest row's prefill AND waits out its decode.
            # The continuous-batching engine exists to remove exactly
            # this.
            vocab = self.engine.cfg.vocab_size
            rows = []
            for p in prompts:
                if isinstance(p, dict):
                    p = p.get("prompt", 0)
                if isinstance(p, (list, tuple)):
                    rows.append([int(t) % vocab for t in p])
                else:
                    rows.append([int(p) % vocab])
            P = prompt_pad or max(len(r) for r in rows)
            ids = np.zeros((len(rows), P), np.int32)
            for b, r in enumerate(rows):
                ids[b, : min(len(r), P)] = r[:P]
            if serve_tracing.batch_active():
                # traced batch: stamp assembly + run the split
                # prefill/decode pair so TTFT/TPOT are real measurements
                serve_tracing.stamp_batch("serve_batch_assembled")
                serve_tracing.set_batch_tokens(new_tokens)
                out = self.engine.generate(
                    ids, new_tokens, stage_cb=serve_tracing.stamp_batch
                )
            else:
                out = self.engine.generate(ids, new_tokens)
            return [out[b].tolist() for b in range(len(prompts))]

        async def __call__(self, prompt):
            return await self.generate(prompt)

        def info(self):
            return {
                "platform": self.platform,
                "params_b": round(self.engine.cfg.num_params() / 1e9, 2),
                "tp": self.engine.tp,
                "shards": self.engine.shard_stats(),
            }

    return LLMDeployment


def engine_llm_deployment(
    model="llama_3b",
    *,
    max_seq_len: Optional[int] = None,
    new_tokens: int = 32,
    num_slots: int = 8,
    page_size: int = 16,
    num_pages: int = 0,
    prefill_chunk: int = 32,
    max_queue: int = 256,
    num_tpus: int = 1,
    tp: Optional[int] = None,
    name: str = "llm",
    autoscaling_config: Optional[dict] = None,
):
    """Continuous-batching counterpart of :func:`llm_deployment`: the
    replica hosts a resident :class:`~ray_tpu.serve.engine.InferenceEngine`
    (iteration-level scheduling over a paged KV cache) instead of the
    whole-request ``@serve.batch`` path.  Requests of any prompt length
    admit/retire per token step, tokens stream incrementally over
    dag-channel token streams (``handle.stream_tokens`` / SSE at the
    proxy), and a full admission queue rejects FAST with
    ``EngineOverloadedError`` (the proxy's 503).  Accepts the same
    prompt wire shapes as the static path plus
    ``{"prompt": [...], "max_new_tokens": n, "eos_token": t}`` dicts."""
    from ray_tpu import serve

    @serve.deployment(
        name=name,
        ray_actor_options={"num_tpus": num_tpus},
        max_concurrent_queries=max(256, max_queue),
        autoscaling_config=autoscaling_config
        or {
            "min_replicas": 1,
            "max_replicas": 1,
            "target_num_ongoing_requests_per_replica": 64,
        },
    )
    class LLMEngineDeployment:
        def __init__(self):
            import jax

            from ray_tpu.serve.engine import EngineConfig, InferenceEngine

            cfg = _resolve_cfg(model, max_seq_len)
            self.llm = ShardedLLM(cfg, tp=tp)
            self.engine = InferenceEngine(
                self.llm,
                EngineConfig(
                    num_slots=num_slots,
                    page_size=page_size,
                    max_seq_len=cfg.max_seq_len,
                    num_pages=num_pages,
                    prefill_chunk=prefill_chunk,
                    max_queue=max_queue,
                    max_new_tokens=new_tokens,
                ),
                deployment=name,
            )
            self.platform = jax.devices()[0].platform

        def _submit(self, prompt, max_new_tokens=None, eos_token=None, sink=None):
            from ray_tpu.serve import tracing as serve_tracing

            ids, spec_new, spec_eos = _parse_prompt_spec(
                prompt, self.llm.cfg.vocab_size, new_tokens
            )
            return self.engine.submit(
                ids,
                max_new_tokens if max_new_tokens is not None else spec_new,
                eos_token=eos_token if eos_token is not None else spec_eos,
                trace=serve_tracing.current_request(),
                sink=sink,
            )

        async def __call__(self, prompt):
            """Buffered (non-streaming) callers: submit and await the full
            sequence without blocking the replica's event loop — the
            engine thread resolves the future at retirement."""
            import asyncio

            from ray_tpu.exceptions import EngineStreamError

            req = self._submit(prompt)
            loop = asyncio.get_running_loop()
            fut = loop.create_future()

            def _done(sink):
                def _fin():
                    if fut.done():
                        return
                    if sink.error is not None:
                        fut.set_exception(EngineStreamError(sink.error))
                    else:
                        fut.set_result(list(sink.tokens))

                loop.call_soon_threadsafe(_fin)

            req.sink.add_done_callback(_done)
            return await fut

        # ---- streaming: dag-channel attach with an actor-call fallback

        def engine_stream_start(self, prompt, max_new_tokens=None, eos_token=None):
            import os

            from ray_tpu.serve.engine import transport

            st = transport.hub().create(
                outbox_limit=self.engine.cfg.stream_outbox_limit
            )
            try:
                req = self._submit(
                    prompt, max_new_tokens, eos_token=eos_token, sink=st
                )
            except BaseException:
                # rejected submit (overload/capacity): reap the stream
                # NOW — gc_finished only sweeps closed streams, and this
                # one would otherwise sit open in the hub forever under
                # exactly the sustained-overload condition
                transport.hub().remove(st.sid)
                raise
            st.cancel_cb = lambda: self.engine.cancel(req)
            return {
                "sid": st.sid,
                "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
            }

        async def engine_stream_next(self, sid, max_frames=16, timeout=30.0):
            """Pull-path fallback (no direct-call transport): drain the
            stream's outbox through the normal actor-call path.  Runs the
            blocking wait on an executor so concurrent requests keep
            flowing through the replica's loop."""
            import asyncio

            from ray_tpu.serve.engine import transport

            st = transport.hub().get(int(sid))
            if st is None:
                return [], True
            frames, done = await asyncio.get_running_loop().run_in_executor(
                None, st.pull, int(max_frames), float(timeout)
            )
            if done:
                transport.hub().remove(int(sid))
            return frames, done

        def engine_stream_state(self, sid):
            """Stream delivery introspection (ops/debug surface): outbox
            depth, writer/ring state, wire cursor."""
            from ray_tpu.serve.engine import transport

            st = transport.hub().get(int(sid))
            if st is None:
                return {"gone": True}
            out = {
                "frames_queued": len(st._frames),
                "attached": st._writer is not None,
                "seq": st._seq,
                "closed": st.closed,
                "finished": st.finished,
            }
            w = st._writer
            if w is not None:
                out.update(
                    {
                        "ring": w._ring is not None,
                        "ring_unusable": w._ring_unusable,
                        "broken": w.broken,
                        "co_located": w._co_located,
                    }
                )
                if w._ring is not None:
                    out["ring_seqs"] = w._ring._seqs()
            return out

        def engine_stream_cancel(self, sid):
            from ray_tpu.serve.engine import transport

            st = transport.hub().get(int(sid))
            if st is not None and st.cancel_cb is not None:
                st.cancel_cb()
            transport.hub().remove(int(sid))
            return True

        # ---- observe / manage

        def engine_stats(self):
            return self.engine.stats()

        def engine_load(self):
            """Cheap pressure snapshot for least-pressure routing
            (serve/FLEET.md): queue depth, slot occupancy, and KV-page
            fraction.  The Replica wrapper merges this into its load()
            report, which the controller piggybacks onto routing
            publishes — called at the load-poll period, so it must stay
            allocation-light."""
            st = self.engine.stats()
            pages_total = float(st.get("pages_total", 0.0) or 0.0)
            return {
                "queue_depth": float(st.get("queue_depth", 0.0)),
                "slots_active": float(st.get("slots_active", 0.0)),
                "slots_total": float(st.get("slots_total", 0.0)),
                "kv_page_frac": (
                    float(st.get("pages_used", 0.0)) / pages_total
                    if pages_total > 0
                    else 0.0
                ),
            }

        def engine_idle(self):
            """Drain-completion predicate (serve/FLEET.md): True only
            when the scheduler holds no queued or running requests AND
            every hub stream's consumer finished draining its outbox —
            a replica torn down earlier would drop frames a slow client
            had not pulled yet."""
            from ray_tpu.serve.engine import transport

            st = self.engine.stats()
            busy = st.get("queue_depth", 0.0) or st.get("slots_active", 0.0)
            return not busy and transport.hub().busy_count() == 0

        def defrag(self):
            return self.engine.defrag()

        def reconfigure(self, user_config):
            """Live knobs only (queue bound for load shedding); geometry
            is baked into compiled programs."""
            if user_config and "max_queue" in user_config:
                self.engine.reconfigure(max_queue=int(user_config["max_queue"]))

        def info(self):
            return {
                "platform": self.platform,
                "params_b": round(self.llm.cfg.num_params() / 1e9, 2),
                "tp": self.llm.tp,
                "engine": self.engine.stats(),
                "shards": self.llm.shard_stats(),
            }

    return LLMEngineDeployment
