"""Tensor-parallel sharded LLM serving engine.

BASELINE config #5 serves Llama-2-7B, and one 16G v5e cannot hold 7B in
bf16 (~13.5 GB weights before the KV cache) — so 7B serving is a MESH
story: weights AND the KV cache are sharded over a ``tp`` axis, the
per-token decode step is jitted once over the mesh with the cache buffers
donated (no double-buffered carry), and XLA inserts the attention/MLP
output-projection psums that ride ICI.  The reference never solves this
inside Serve — its replicas wrap user torch modules and model sharding
happens outside (reference: python/ray/serve/_private/replica.py:58);
here the sharded engine IS the replica's model, so a deployment scales
from one chip (tp=1) to a pod slice by changing one argument.

Sharding layout (megatron-style, from LlamaModel.param_pspecs):
  wq/wk/wv/w_gate/w_up : [L, E, out]  — out (heads / ffn) split over tp
  wo/w_down            : [L, in, E]   — in split over tp (psum after)
  tok_emb / out_head   : vocab split over tp (psum gather / sharded logits)
  KV cache             : [L, B, S, KV, D] — KV heads split over tp
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ray_tpu.models.llama import LlamaConfig, LlamaModel

__all__ = ["ShardedLLM", "llm_deployment"]


def _filter_spec(spec, axis_names):
    """Drop mesh axes the serving mesh doesn't have (e.g. the training
    pspecs name fsdp; a pure-tp serving mesh replicates those dims)."""
    from jax.sharding import PartitionSpec as P

    return P(*(a if a in axis_names else None for a in spec))


class ShardedLLM:
    """A llama-family model sharded over a 1-D tp mesh, ready to decode.

    init:
      "random" — normal(0, 0.02) weights (bench/serving without a ckpt)
      "cheap"  — deterministic iota-pattern fill (dryrun at 7B shape: no
                 7-billion-sample RNG on a 1-core host; still exercises
                 every collective with non-trivial values)
      dict     — a params pytree (or host arrays) to shard onto the mesh
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        devices: Optional[Sequence[Any]] = None,
        tp: Optional[int] = None,
        init: Any = "random",
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        devices = list(devices if devices is not None else jax.devices())
        tp = int(tp or len(devices))
        if tp > len(devices):
            raise ValueError(f"tp={tp} but only {len(devices)} devices")
        for dim, name in (
            (cfg.n_kv_heads, "n_kv_heads"),
            (cfg.hidden_dim, "hidden_dim"),
            (cfg.padded_vocab, "padded_vocab"),
            (cfg.dim, "dim"),
        ):
            if dim % tp:
                raise ValueError(f"{name}={dim} not divisible by tp={tp}")
        self.cfg = cfg
        self.tp = tp
        self.model = LlamaModel(cfg)
        self.mesh = Mesh(np.array(devices[:tp]), ("tp",))

        pspecs = self.model.param_pspecs()
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, _filter_spec(s, ("tp",))),
            pspecs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.cache_sharding = NamedSharding(self.mesh, P(None, None, None, "tp", None))
        self._repl = NamedSharding(self.mesh, P())

        shapes = jax.eval_shape(self.model.init, jax.random.PRNGKey(seed))
        if isinstance(init, dict):
            self.params = jax.tree.map(
                lambda x, sh: jax.device_put(x, sh), init, self.param_shardings
            )
        elif init == "cheap":
            # deterministic per-shard numpy fill via make_array_from_callback
            # — no XLA init program, no 2x cast transients; each device
            # writes only ITS shard.  Values vary over the last two dims
            # (broadcast over leading), which is non-degenerate enough to
            # exercise every collective with real data at 7B shape on a
            # 1-core dryrun host in tens of seconds.
            import zlib

            def fill(path, s, sharding):
                if "norm" in path:
                    return jax.make_array_from_callback(
                        s.shape,
                        sharding,
                        lambda idx: np.ones(
                            tuple(
                                len(range(*sl.indices(d)))
                                for sl, d in zip(idx, s.shape)
                            ),
                            s.dtype,
                        ),
                    )
                salt = zlib.crc32(path.encode())

                def cb(idx):
                    sl = [range(*x.indices(d)) for x, d in zip(idx, s.shape)]
                    shape = tuple(len(r) for r in sl)
                    j = np.arange(sl[-1].start, sl[-1].stop, dtype=np.int64)
                    col = ((j * 2654435761 + salt) % 1009) / 1009.0 - 0.5
                    if len(shape) >= 2:
                        i = np.arange(sl[-2].start, sl[-2].stop, dtype=np.int64)
                        row = ((i * 40503 + salt) % 997) / 997.0 - 0.5
                        mat = (col[None, :] + row[:, None]) * 0.02
                    else:
                        mat = col * 0.02
                    out = np.broadcast_to(mat, shape).astype(s.dtype)
                    return np.ascontiguousarray(out)

                return jax.make_array_from_callback(s.shape, sharding, cb)

            params = {}
            for k, v in shapes.items():
                if isinstance(v, dict):
                    params[k] = {
                        k2: fill(k2, s, self.param_shardings[k][k2])
                        for k2, s in v.items()
                    }
                else:
                    params[k] = fill(k, v, self.param_shardings[k])
            self.params = params
        elif init == "random":
            self.params = jax.jit(
                self.model.init, out_shardings=self.param_shardings
            )(jax.random.PRNGKey(seed))
        else:
            raise ValueError(f"unknown init {init!r}")

        model = self.model

        def prefill(params, cache, prompt_t):
            """Teacher-forced scan over prompt positions; returns the cache
            and the last position's logits.  prompt_t: [P, B, 1]."""

            def body(carry, xt):
                cache, _ = carry
                t, tok = xt
                logits, cache = model.decode_step(params, cache, tok, t)
                return (cache, logits), None

            P_len = prompt_t.shape[0]
            ts = jnp.arange(P_len)
            init_logits = jnp.zeros(
                (prompt_t.shape[1], cfg.padded_vocab), cfg.compute_dtype
            )
            (cache, logits), _ = jax.lax.scan(
                body, (cache, init_logits), (ts, prompt_t)
            )
            return cache, logits

        def generate_from(params, cache, logits, start_pos, n_new):
            """Greedy decode n_new tokens starting from prefill logits
            (n_new is static: the scan length is baked into the program)."""

            def body(carry, t):
                tok, cache = carry
                logits, cache = model.decode_step(params, cache, tok, t)
                nxt = jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
                return (nxt, cache), nxt[:, 0]

            first = jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
            if n_new == 1:
                return first, cache
            (_, cache), toks = jax.lax.scan(
                body, (first, cache), start_pos + jnp.arange(n_new - 1)
            )
            return jnp.concatenate([first.T, toks], axis=0).T, cache

        def full_generate(params, cache, prompt_t, n_new):
            cache, logits = prefill(params, cache, prompt_t)
            toks, cache = generate_from(
                params, cache, logits, prompt_t.shape[0], n_new
            )
            return toks

        # ONE compiled program per (B, P, n_new): prompt scan + decode scan
        # stay on-chip (per-token host dispatch would be RPC-bound over the
        # axon tunnel); the cache is created outside and DONATED so XLA
        # updates it in place instead of double-buffering the scan carry
        # (the r4 B=16 HBM cliff).
        self._generate = jax.jit(full_generate, static_argnums=(3,), donate_argnums=(1,))
        # split pair for the traced serving path: prefill and decode as
        # separate programs so the first token's logits are a HOST-VISIBLE
        # boundary — what TTFT/TPOT measure (and the baseline the
        # continuous-batching engine has to beat).  jit objects are lazy:
        # untraced callers (dryrun, bench fused path) never compile these.
        self._prefill = jax.jit(prefill, donate_argnums=(1,))
        self._decode = jax.jit(
            generate_from, static_argnums=(4,), donate_argnums=(1,)
        )
        self._init_cache = jax.jit(
            self.model.init_cache, static_argnums=(0,), out_shardings=self.cache_sharding
        )
        self._jnp = jnp

    # ------------------------------------------------------------------ api

    def generate(self, prompts: np.ndarray, n_new: int, stage_cb=None) -> np.ndarray:
        """prompts [B, P] int32 → generated tokens [B, n_new] (greedy).

        ``stage_cb(phase)`` opts into the SPLIT prefill/decode pair so the
        first token is a host-visible boundary: the callback fires with
        ``serve_prefill_start`` / ``serve_first_token`` /
        ``serve_decode_end`` (canonical task_events names — the serve
        tracer's stamp_batch slots straight in).  Without it the fused
        one-program path runs unchanged."""
        import jax

        jnp = self._jnp
        prompts = np.asarray(prompts, np.int32)
        B, P_len = prompts.shape
        if P_len + n_new > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt {P_len} + new {n_new} exceeds max_seq_len {self.cfg.max_seq_len}"
            )
        cache = self._init_cache(B)
        prompt_t = jnp.asarray(prompts.T[:, :, None])  # [P, B, 1]
        if stage_cb is None:
            toks = self._generate(self.params, cache, prompt_t, int(n_new))
            return np.asarray(toks)
        stage_cb("serve_prefill_start")
        cache, logits = self._prefill(self.params, cache, prompt_t)
        # first token's logits resident on host clock = TTFT endpoint
        jax.block_until_ready(logits)
        stage_cb("serve_first_token")
        toks, cache = self._decode(self.params, cache, logits, P_len, int(n_new))
        toks = np.asarray(toks)  # device→host sync: decode truly done
        stage_cb("serve_decode_end")
        return toks

    def param_count(self) -> int:
        import jax

        return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self.params))

    def shard_stats(self) -> Dict[str, Any]:
        """Total param bytes and per-device resident bytes — the evidence
        that the model actually lives 1/tp per chip."""
        import jax

        total = 0
        per_device: Dict[str, int] = {}
        for leaf in jax.tree_util.tree_leaves(self.params):
            total += leaf.nbytes
            for sh in leaf.addressable_shards:
                key = str(sh.device)
                per_device[key] = per_device.get(key, 0) + sh.data.nbytes
        return {"total_bytes": total, "per_device_bytes": per_device}


def llm_deployment(
    model="llama_3b",
    *,
    max_seq_len: Optional[int] = None,
    new_tokens: int = 32,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.02,
    num_tpus: int = 1,
    tp: Optional[int] = None,
    autoscaling_config: Optional[dict] = None,
):
    """Build a Serve deployment wrapping a ShardedLLM replica.

    ``model`` is a LlamaConfig constructor name ("llama_3b", "llama2_7b",
    ...) or a LlamaConfig INSTANCE (resolved worker-side either way —
    pass an instance for configs the name registry doesn't have).  The
    replica claims ``num_tpus`` chips and shards over every device jax
    exposes inside the actor (tp defaults to all of them) — the same code
    path serves llama_3b on one chip and llama2_7b on a mesh."""
    from ray_tpu import serve

    @serve.deployment(
        name="llm",
        ray_actor_options={"num_tpus": num_tpus},
        max_concurrent_queries=64,
        autoscaling_config=autoscaling_config
        or {
            "min_replicas": 1,
            "max_replicas": 1,
            "target_num_ongoing_requests_per_replica": 32,
        },
    )
    class LLMDeployment:
        def __init__(self):
            import dataclasses

            import jax
            import jax.numpy as jnp

            if isinstance(model, LlamaConfig):
                # an explicit max_seq_len overrides; otherwise the
                # instance's own value stands
                cfg = (
                    model
                    if max_seq_len is None
                    else dataclasses.replace(model, max_seq_len=max_seq_len)
                )
            else:
                cfg = getattr(LlamaConfig, model)(
                    max_seq_len=max_seq_len or 256, param_dtype=jnp.bfloat16
                )
            self.engine = ShardedLLM(cfg, tp=tp)
            self.platform = jax.devices()[0].platform

        @serve.batch(
            max_batch_size=max_batch_size, batch_wait_timeout_s=batch_wait_timeout_s
        )
        async def generate(self, prompts):
            from ray_tpu.serve import tracing as serve_tracing

            ids = np.asarray(
                [[int(p) % self.engine.cfg.vocab_size] for p in prompts]
                + [[0]] * (max_batch_size - len(prompts)),
                np.int32,
            )
            if serve_tracing.batch_active():
                # traced batch: stamp assembly + run the split
                # prefill/decode pair so TTFT/TPOT are real measurements
                serve_tracing.stamp_batch("serve_batch_assembled")
                serve_tracing.set_batch_tokens(new_tokens)
                out = self.engine.generate(
                    ids, new_tokens, stage_cb=serve_tracing.stamp_batch
                )
            else:
                out = self.engine.generate(ids, new_tokens)
            return [out[b].tolist() for b in range(len(prompts))]

        async def __call__(self, prompt):
            return await self.generate(prompt)

        def info(self):
            return {
                "platform": self.platform,
                "params_b": round(self.engine.cfg.num_params() / 1e9, 2),
                "tp": self.engine.tp,
                "shards": self.engine.shard_stats(),
            }

    return LLMDeployment
