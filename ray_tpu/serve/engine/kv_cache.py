"""Slotted/paged KV cache for the continuous-batching engine.

The device side is ONE fixed page pool per replica —
``[L, num_pages, page_size, KV, D]`` K/V buffers (KV heads sharded over
the tp mesh axis, same layout the contiguous serving cache uses) — and
the host side is this module: a page allocator plus per-slot page tables
mapping each sequence's logical pages onto physical pool pages.  Because
every jitted engine program is shaped by (num_slots, pages_per_slot,
page_size) only, sequences of wildly different lengths share one
compiled decode step and the pool stays donated/in-place (the jit-shape
invariant; engine/DESIGN.md).

Layout follows the TPU paged-attention kernel convention (page pools +
``page_indices`` + lengths) so the gather-based reference attention in
models/llama.py can later be swapped for the pallas kernel without
touching this bookkeeping.

Allocation policy: admission RESERVES a request's worst case
(ceil((prompt + max_new_tokens) / page_size) pages) up front, so a
running sequence can never hit out-of-pages mid-decode — pool pressure
blocks *admission* (requests wait in the queue), it never crashes or
preempts an in-flight stream.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.util.lockwitness import named_lock

import numpy as np

__all__ = ["PageAllocator", "PagedKVCache"]


class PageAllocator:
    """Free-list allocator over the physical page pool (host-side only;
    page CONTENTS live on device).  Lowest-id-first allocation keeps the
    pool dense from the front, which keeps compaction moves short."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free = sorted(range(self.num_pages), reverse=True)  # pop() -> lowest id

    # ------------------------------------------------------------- alloc

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return self.num_pages - len(self._free)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` cache entries."""
        return max(1, math.ceil(tokens / self.page_size))

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages, or None when the pool can't satisfy the
        request — the caller blocks ADMISSION on None; this never raises
        for exhaustion."""
        if n <= 0:
            return []
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise ValueError(f"page {p} outside pool [0, {self.num_pages})")
        live = set(self._free)
        dup = [p for p in pages if p in live]
        if dup:
            raise ValueError(f"double free of pages {dup}")
        self._free.extend(pages)
        # keep pop() returning the lowest free id (reverse-sorted stack)
        self._free.sort(reverse=True)

    # ------------------------------------------------------------ defrag

    def fragmentation(self) -> float:
        """0.0 = the free space is one contiguous run, 1.0 = maximally
        scattered.  Indirection through page tables makes fragmentation
        harmless for correctness; the metric (and compaction) exist for
        HBM locality and for shrinking the pool live."""
        nfree = len(self._free)
        if nfree <= 1:
            return 0.0
        ids = sorted(self._free)
        longest = run = 1
        for a, b in zip(ids, ids[1:]):
            run = run + 1 if b == a + 1 else 1
            longest = max(longest, run)
        return 1.0 - longest / nfree

    def compaction_plan(self, allocated: List[int]) -> List[Tuple[int, int]]:
        """Plan a defrag: moves ``[(src, dst), ...]`` relocating allocated
        pages down into the lowest ids so the free tail becomes one
        contiguous run.  Pure planning — the engine applies the moves as a
        device copy and rewrites page tables, then calls
        :meth:`apply_compaction`."""
        alloc_sorted = sorted(set(allocated))
        moves: List[Tuple[int, int]] = []
        for dst, src in enumerate(alloc_sorted):
            if src != dst:
                moves.append((src, dst))
        return moves

    def apply_compaction(self, n_allocated: int) -> None:
        """After the engine applied a compaction plan: allocated pages now
        occupy ids [0, n_allocated); rebuild the free list as the tail."""
        self._free = sorted(range(n_allocated, self.num_pages), reverse=True)


class PagedKVCache:
    """Host-side view of one replica's page pool: the allocator plus the
    per-slot page-table matrix handed to every jitted engine call.

    ``tables`` is a ``[num_slots, pages_per_slot]`` int32 array, -1 for
    unallocated logical pages — exactly the argument shape
    ``decode_step_paged`` consumes, so the engine passes ``cache.tables``
    straight through.  All mutation happens on the engine loop thread;
    ``stats()`` may be read from other threads (snapshot semantics only).
    """

    def __init__(self, num_slots: int, pages_per_slot: int, num_pages: int, page_size: int):
        if num_slots <= 0 or pages_per_slot <= 0:
            raise ValueError("num_slots and pages_per_slot must be positive")
        self.num_slots = int(num_slots)
        self.pages_per_slot = int(pages_per_slot)
        self.page_size = int(page_size)
        self.allocator = PageAllocator(num_pages, page_size)
        self.tables = np.full((self.num_slots, self.pages_per_slot), -1, np.int32)
        self._slot_pages: Dict[int, List[int]] = {}
        self._lock = named_lock("PagedKVCache._lock")

    @property
    def max_tokens_per_slot(self) -> int:
        return self.pages_per_slot * self.page_size

    def reserve(self, slot: int, tokens: int) -> bool:
        """Reserve enough pages on ``slot`` for ``tokens`` total cache
        entries.  False = pool exhausted (admission must wait); raises only
        on a capacity bug (tokens beyond the slot's logical span)."""
        need = self.allocator.pages_for(tokens)
        if need > self.pages_per_slot:
            raise ValueError(
                f"{tokens} tokens need {need} pages > pages_per_slot "
                f"{self.pages_per_slot}"
            )
        with self._lock:
            have = self._slot_pages.get(slot, [])
            extra = need - len(have)
            if extra <= 0:
                return True
            pages = self.allocator.alloc(extra)
            if pages is None:
                return False
            self.tables[slot, len(have) : len(have) + extra] = pages
            self._slot_pages[slot] = have + pages
            return True

    def release(self, slot: int) -> None:
        """Free a retired slot's pages and clear its table row — slot
        recycling is what lets the next queued request admit without a new
        compile or a pool grow."""
        with self._lock:
            pages = self._slot_pages.pop(slot, [])
            if pages:
                self.allocator.free(pages)
            self.tables[slot, :] = -1

    def slot_pages(self, slot: int) -> List[int]:
        with self._lock:
            return list(self._slot_pages.get(slot, []))

    # ------------------------------------------------------------ defrag

    def compaction_plan(self) -> List[Tuple[int, int]]:
        with self._lock:
            allocated = [p for pages in self._slot_pages.values() for p in pages]
            return self.allocator.compaction_plan(allocated)

    def apply_compaction(self, moves: List[Tuple[int, int]]) -> None:
        """Rewrite page tables after the engine moved page CONTENTS on
        device (engine.defrag owns the device copy)."""
        if not moves:
            return
        remap = {src: dst for src, dst in moves}
        with self._lock:
            n_alloc = 0
            for slot, pages in self._slot_pages.items():
                newpages = [remap.get(p, p) for p in pages]
                self._slot_pages[slot] = newpages
                self.tables[slot, : len(newpages)] = newpages
                n_alloc += len(newpages)
            self.allocator.apply_compaction(n_alloc)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "pages_total": float(self.allocator.num_pages),
                "pages_used": float(self.allocator.used),
                "page_size": float(self.page_size),
                "fragmentation": self.allocator.fragmentation(),
                "slots_with_pages": float(len(self._slot_pages)),
            }
