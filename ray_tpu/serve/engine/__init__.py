"""Continuous-batching LLM inference engine (iteration-level scheduling,
paged KV cache, dag-channel token streaming).  See DESIGN.md."""

from ray_tpu.exceptions import EngineOverloadedError, EngineStreamError  # noqa: F401
from ray_tpu.serve.engine.kv_cache import PageAllocator, PagedKVCache  # noqa: F401
from ray_tpu.serve.engine.loop import (  # noqa: F401
    BufferSink,
    EngineConfig,
    InferenceEngine,
)
from ray_tpu.serve.engine.scheduler import EngineRequest, EngineScheduler  # noqa: F401
