"""Iteration-level admission/retirement for the continuous-batching engine.

The unit of scheduling is one TOKEN STEP, not one request (the
iteration-level batching of Orca/vLLM, vs. the whole-request
``@serve.batch`` path this engine replaces): every engine iteration the
scheduler admits queued requests into free slots (page reservation
gating), feeds at most one chunk of one prompt through prefill, decodes
every slot already streaming, and retires sequences that hit EOS or
their token budget — freeing the slot and its pages for the next queued
request in the same iteration.

Separation of concerns: this module is pure host-side bookkeeping (no
jax, no threads — the engine loop owns the lock and the device); that is
what makes admit/retire/EOS semantics unit-testable on nothing but a
fake clock.
"""

from __future__ import annotations

import collections
import itertools
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from ray_tpu.exceptions import EngineOverloadedError
from ray_tpu.serve.engine.kv_cache import PagedKVCache

__all__ = ["EngineRequest", "EngineScheduler"]

# request lifecycle states
QUEUED = "QUEUED"  # accepted, waiting for a slot + pages
PREFILL = "PREFILL"  # slot assigned, prompt entering the cache chunk-wise
DECODE = "DECODE"  # first token produced, streaming one token per step
DONE = "DONE"  # retired: EOS / max tokens / cancelled
FAILED = "FAILED"  # retired with an error


@dataclass
class EngineRequest:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None
    state: str = QUEUED
    slot: int = -1
    fill: int = 0  # prompt tokens already written to the cache
    out: List[int] = field(default_factory=list)
    trace: Optional[dict] = None
    sink: Optional[object] = None  # delivery sink (engine/loop.py)
    error: Optional[str] = None
    cancelled: bool = False
    t_submit: float = field(default_factory=time.time)

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def done(self) -> bool:
        return self.state in (DONE, FAILED)


class EngineScheduler:
    """Admission queue + per-slot run table.

    NOT thread-safe by itself: the engine serializes every call under its
    own lock (submit from actor threads, everything else from the loop
    thread)."""

    def __init__(
        self,
        cache: PagedKVCache,
        *,
        max_queue: int = 256,
        prefill_chunk: int = 32,
    ):
        self.cache = cache
        self.max_queue = int(max_queue)
        self.prefill_chunk = int(prefill_chunk)
        self.queue: Deque[EngineRequest] = collections.deque()
        self.running: Dict[int, EngineRequest] = {}  # slot -> request
        self._free_slots = list(range(cache.num_slots - 1, -1, -1))  # pop() -> slot 0 first
        self._rid = itertools.count(1)
        # counters for the stats/gauge plane
        self.n_done = 0
        self.n_failed = 0
        self.n_tokens = 0

    # ------------------------------------------------------------- intake

    def submit(
        self,
        prompt: List[int],
        max_new_tokens: int,
        eos_token: Optional[int] = None,
        trace: Optional[dict] = None,
        sink=None,
    ) -> EngineRequest:
        """Accept a request into the bounded admission queue.  A full
        queue raises :class:`EngineOverloadedError` IMMEDIATELY — the
        bounded failure mode the HTTP proxy turns into 503+Retry-After
        (unbounded queueing is exactly the p99 cliff this engine exists
        to remove)."""
        if not prompt:
            raise ValueError("empty prompt")
        total = len(prompt) + int(max_new_tokens)
        if total > self.cache.max_tokens_per_slot:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds the engine's per-sequence capacity "
                f"{self.cache.max_tokens_per_slot}"
            )
        if len(self.queue) >= self.max_queue:
            raise EngineOverloadedError(
                f"engine admission queue full ({self.max_queue} waiting)",
                retry_after_s=1.0,
            )
        req = EngineRequest(
            rid=next(self._rid),
            prompt=list(prompt),
            max_new_tokens=int(max_new_tokens),
            eos_token=eos_token,
            trace=trace,
            sink=sink,
        )
        self.queue.append(req)
        return req

    def admit(self) -> List[EngineRequest]:
        """Move queued requests into free slots while the page pool can
        cover their worst case.  FCFS with head-of-line blocking ON
        PURPOSE: skipping a big request to admit later small ones forever
        would starve it.  Out of pages → the head request WAITS (admission
        blocked, never a crash); retirement frees pages and unblocks it."""
        admitted: List[EngineRequest] = []
        while self.queue and self._free_slots:
            req = self.queue[0]
            slot = self._free_slots[-1]
            if not self.cache.reserve(slot, req.prompt_len + req.max_new_tokens):
                break  # pool pressure: block admission, keep the request queued
            self._free_slots.pop()
            self.queue.popleft()
            req.slot = slot
            req.state = PREFILL
            self.running[slot] = req
            admitted.append(req)
        return admitted

    # ------------------------------------------------------------- planning

    def next_prefill(self) -> Optional[Tuple[EngineRequest, int, List[int]]]:
        """The next prompt chunk to run: (request, start_pos, tokens),
        FCFS among PREFILL requests, at most ``prefill_chunk`` tokens — a
        long prompt runs as many chunks across many iterations, and the
        decode fleet advances between every pair (chunked prefill: long
        prompts never stall in-flight streams)."""
        cand = [r for r in self.running.values() if r.state == PREFILL]
        if not cand:
            return None
        req = min(cand, key=lambda r: r.rid)
        start = req.fill
        toks = req.prompt[start : start + self.prefill_chunk]
        return req, start, toks

    def note_prefill(self, req: EngineRequest, n_tokens: int) -> bool:
        """Advance a request's prefill cursor; True when the prompt is now
        fully resident (the chunk's sampled token becomes the first
        generated token and the request joins the decode fleet)."""
        req.fill += int(n_tokens)
        return req.fill >= req.prompt_len

    def decode_fleet(self) -> List[EngineRequest]:
        return [r for r in self.running.values() if r.state == DECODE]

    # ----------------------------------------------------------- lifecycle

    def note_token(self, req: EngineRequest, token: int) -> bool:
        """Record one generated token; True when the sequence retires
        (EOS or budget).  The caller delivers the token and, on True,
        calls :meth:`retire`."""
        req.out.append(int(token))
        self.n_tokens += 1
        if req.eos_token is not None and int(token) == int(req.eos_token):
            return True
        return len(req.out) >= req.max_new_tokens

    def drop_cancelled_queued(self) -> List[EngineRequest]:
        """Remove cancelled requests still waiting in the queue (the
        engine seals + delivers their done frames; dropping them here
        alone would strand their consumers)."""
        victims = [r for r in self.queue if r.cancelled]
        if victims:
            self.queue = collections.deque(r for r in self.queue if not r.cancelled)
            for req in victims:
                self._finish(req, DONE, error=None)
        return victims

    def retire(self, req: EngineRequest, error: Optional[str] = None) -> None:
        """Retire a running request: recycle its slot and pages so the
        next queued request can admit on the SAME iteration."""
        if req.slot >= 0:
            self.cache.release(req.slot)
            self.running.pop(req.slot, None)
            self._free_slots.append(req.slot)
            req.slot = -1
        self._finish(req, FAILED if error else DONE, error=error)

    def _finish(self, req: EngineRequest, state: str, error: Optional[str]) -> None:
        req.state = state
        req.error = error
        if state == FAILED:
            self.n_failed += 1
        else:
            self.n_done += 1

    def fail_all(self, reason: str) -> List[EngineRequest]:
        """Engine shutdown / fatal device error: retire everything with a
        typed error so no caller hangs on a silent stream."""
        victims = list(self.running.values()) + list(self.queue)
        self.queue.clear()
        for req in list(self.running.values()):
            self.retire(req, error=reason)
        for req in victims:
            if not req.done:
                self._finish(req, FAILED, error=reason)
        return victims

    # ------------------------------------------------------------- stats

    def depth(self) -> int:
        return len(self.queue)

    def active(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.queue or self.running)

    def stats(self) -> Dict[str, float]:
        by_state: Dict[str, int] = {}
        for r in self.running.values():
            by_state[r.state] = by_state.get(r.state, 0) + 1
        return {
            "queue_depth": float(len(self.queue)),
            "slots_total": float(self.cache.num_slots),
            "slots_active": float(len(self.running)),
            "slots_prefill": float(by_state.get(PREFILL, 0)),
            "slots_decode": float(by_state.get(DECODE, 0)),
            "requests_done": float(self.n_done),
            "requests_failed": float(self.n_failed),
            "tokens_generated": float(self.n_tokens),
        }
