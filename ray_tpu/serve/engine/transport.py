"""Token streaming over compiled-DAG channels.

Per-step token frames leave the engine on the SAME transport the
compiled-DAG subsystem proved out (dag/channel.py): the consumer (HTTP
proxy or a bare DeploymentHandle) dials the replica's direct-call server
once, sends one ``ENGINE_STREAM`` attach frame, and from then on every
frame is a ``ChannelWriter.write`` — a shm-ring slot for co-located
pairs (no socket frame at all on the hot path), an inline ``DAG_PUSH``
cross-node.  No head round-trip, no per-frame actor RPC: the per-token
delivery cost is what PAPERS.md §1/§2 say it must be — ~zero host
dispatch.

Backpressure: a co-located consumer that stops draining fills its ring;
the engine's flush uses ``try_write`` (never blocks the decode fleet on
one slow stream) and parks the frames in a bounded outbox.  A consumer
that stays behind past the bound is BROKEN by contract: the stream's
conn is severed, which surfaces as a typed
:class:`~ray_tpu.exceptions.EngineStreamError` at the consumer — same
fail-loud philosophy as the DAG channels' no-retransmit rule.

Failure: a killed replica (or any transport loss) fires the consumer
conn's close callback → the reader wakes broken → the iterator raises
``EngineStreamError``.  Never a hang.

The fallback for environments without direct-call servers (client mode,
tests with the feature off) is the pull path: the same outbox served by
the ``engine_stream_next`` actor method.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.exceptions import EngineStreamError
from ray_tpu.util.lockwitness import named_condition, named_lock

__all__ = ["StreamHub", "StreamState", "TokenStream", "hub", "open_token_stream"]


class StreamState:
    """One stream's delivery state: the engine's sink AND the wire end.

    Engine thread: ``emit`` / ``flush``.  Worker io loop: ``attach`` /
    ``detach``.  Actor executor threads: ``pull`` (fallback path).  The
    outbox deque + condvar serialize all of them.
    """

    def __init__(self, sid: int, outbox_limit: int = 4096):
        self.sid = sid
        self._limit = int(outbox_limit)
        self._frames: collections.deque = collections.deque()
        self._cv = named_condition("StreamState._cv")
        self._flush_lock = named_lock("StreamState._flush_lock")
        self._writer = None
        self._conn = None
        self._seq = 0
        self.closed = False
        self.finished = False  # done frame queued (and, once flushed, sent)
        self.cancel_cb = None  # engine wires this to cancel the request

    # ------------------------------------------------------------- engine

    def emit(self, frame: dict) -> None:
        """Engine-side sink: queue one frame and push it toward the
        consumer.  Raising here tells the engine loop to drop the sink;
        a past-the-bound outbox severs the stream instead (typed error at
        the consumer) so the engine keeps a strict delivery bound."""
        with self._cv:
            if self.closed:
                return
            self._frames.append(frame)
            if frame.get("done"):
                self.finished = True
            over = len(self._frames) > self._limit
            self._cv.notify_all()
        if over:
            self.fail("stream consumer fell behind the backpressure bound")
            return
        self.flush()

    def needs_flush(self) -> bool:
        """Frames waiting for the wire?  The engine polls this to keep
        re-flushing streams whose ring filled (try_write returned False):
        the ring is only ``dag_channel_slots`` deep, so any stream longer
        than the ring NEEDS these retries once the consumer drains slots —
        emit() alone stops flushing the moment generation finishes."""
        with self._cv:
            return bool(self._frames) and not self.closed

    def flushable(self) -> bool:
        """True when a flush can make progress RIGHT NOW (writer
        attached).  Pull-path streams queue frames without a writer —
        they drain via pull(), so the engine's fast retry tick skips
        them."""
        return self._writer is not None

    def flush(self) -> None:
        """Drain queued frames into the channel writer (no-op before
        attach / on the pull path).  try_write keeps this non-blocking:
        a full ring leaves the frame queued for the next flush."""
        from ray_tpu.dag.channel import ChannelBrokenError, encode_value

        writer = self._writer
        if writer is None:
            return
        with self._flush_lock:
            while True:
                with self._cv:
                    if not self._frames:
                        return
                    frame = self._frames[0]
                try:
                    wire, nbytes = encode_value(frame)
                    if not writer.try_write(self._seq, wire, nbytes):
                        return  # ring full: retry on the next emit/tick
                except ChannelBrokenError:
                    self.close()
                    return
                self._seq += 1
                with self._cv:
                    self._frames.popleft()
                if frame.get("done"):
                    # do NOT close here: the done frame may still be
                    # sitting unread in the ring (a fast sequence finishes
                    # before the attach reply even reaches the consumer),
                    # and closing the writer would delete the unpinned
                    # ring with every frame in it.  The consumer drains at
                    # its own pace; its conn close (TokenStream.close →
                    # hub.on_conn_lost) reclaims the writer and ring.
                    return

    # ----------------------------------------------------------- transport

    def attach(self, writer, conn) -> dict:
        """io-loop: a consumer attached a dag channel.  First flush runs
        here so frames buffered pre-attach go out immediately."""
        with self._cv:
            if self.closed:
                return {"ok": False, "error": "stream already closed"}
            if self._writer is not None:
                return {"ok": False, "error": "stream already has a consumer"}
            self._writer = writer
            self._conn = conn
        self.flush()
        return {"ok": True}

    def fail(self, reason: str) -> None:
        """Sever the stream: the consumer's conn-loss callback turns this
        into a typed EngineStreamError (never a silent stall).  A pull
        consumer has no conn to lose, so the error travels as a final
        frame in the outbox — pull() drains it and the client raises,
        instead of mistaking the truncated stream for a clean finish."""
        with self._cv:
            if not self.closed:
                self._frames.append({"t": [], "done": True, "error": reason})
            self._cv.notify_all()
        conn = self._conn
        self.close()
        if conn is not None and not getattr(conn, "closed", False):
            try:
                from ray_tpu._private import worker as worker_mod

                worker_mod._require_connected().io.loop.call_soon_threadsafe(conn.close)
            except Exception:  # noqa: BLE001 -- teardown path; consumer still sees conn loss
                pass

    def close(self) -> None:
        with self._cv:
            self.closed = True
            self._cv.notify_all()
        writer, self._writer = self._writer, None
        self._conn = None
        if writer is not None:
            writer.close()

    # ------------------------------------------------------ fallback pull

    def pull(self, max_frames: int = 16, timeout: float = 30.0) -> Tuple[List[dict], bool]:
        """Fallback consumer path (engine_stream_next actor method):
        block for the next frame(s); (frames, stream_done)."""
        out: List[dict] = []
        with self._cv:
            if not self._frames and not self.closed:
                self._cv.wait(timeout)
            while self._frames and len(out) < max_frames:
                out.append(self._frames.popleft())
            done = (self.closed and not self._frames) or any(
                f.get("done") for f in out
            )
        return out, done


class StreamHub:
    """Per-process registry: stream id → StreamState.  The worker's
    direct-call server routes ENGINE_STREAM frames here (one hub per
    process, engines register their streams on it)."""

    def __init__(self):
        self._streams: Dict[int, StreamState] = {}
        self._lock = named_lock("StreamHub._lock")
        self._next = 1

    def create(self, outbox_limit: int = 4096, cancel_cb=None) -> StreamState:
        self.gc_finished()  # reap streams severed without a conn (overflow fail)
        with self._lock:
            sid = self._next
            self._next += 1
            st = StreamState(sid, outbox_limit)
            st.cancel_cb = cancel_cb
            self._streams[sid] = st
            return st

    def get(self, sid: int) -> Optional[StreamState]:
        with self._lock:
            return self._streams.get(sid)

    def remove(self, sid: int) -> None:
        with self._lock:
            st = self._streams.pop(sid, None)
        if st is not None:
            st.close()

    def on_conn_lost(self, conn) -> None:
        """Worker io loop: a consumer conn died (orderly close after the
        done frame, or a vanished client).  Close and drop every stream
        riding it — this is where writers and rings are reclaimed."""
        with self._lock:
            victims = [
                sid for sid, st in self._streams.items() if st._conn is conn
            ]
            states = [self._streams.pop(sid) for sid in victims]
        for st in states:
            cb = st.cancel_cb
            if cb is not None and not st.finished:
                try:
                    cb()  # consumer vanished mid-stream: stop generating
                except Exception:  # noqa: BLE001 -- engine may already have retired it
                    pass
            st.close()

    def gc_finished(self) -> None:
        with self._lock:
            dead = [sid for sid, st in self._streams.items() if st.closed]
            for sid in dead:
                self._streams.pop(sid, None)

    def busy_count(self) -> int:
        """Streams whose consumer has not finished draining — the drain
        protocol's wait condition (serve/FLEET.md): a replica may not
        tear down while a live stream's queued frames could still be
        lost.  A finished-but-unclosed stream counts: its done frame is
        out, but the consumer may still be pulling the ring tail."""
        self.gc_finished()
        with self._lock:
            return len(self._streams)


_hub: Optional[StreamHub] = None
_hub_lock = named_lock("ray_tpu.serve.engine.transport._hub_lock")


def hub() -> StreamHub:
    global _hub
    with _hub_lock:
        if _hub is None:
            _hub = StreamHub()
        return _hub


def conn_lost(conn) -> None:
    """Direct-server hook (core/worker_main.py): reclaim streams whose
    consumer conn just died.  No-op in processes that never hosted an
    engine (the caller guards on the module being imported at all)."""
    h = _hub
    if h is not None:
        h.on_conn_lost(conn)


async def handle_frame(payload: dict, conn) -> dict:
    """Worker io-loop entry point: one ENGINE_STREAM control frame from a
    consumer-dialed conn (core/worker_main.py routes here)."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.dag.channel import ChannelWriter

    op = str(payload.get("op", ""))
    sid = int(payload.get("sid", 0))
    h = _hub
    st = h.get(sid) if h is not None else None
    if op == "cancel":
        if st is not None:
            cb = st.cancel_cb
            if cb is not None:
                try:
                    cb()
                except Exception:  # noqa: BLE001 -- consumer is leaving either way
                    pass
            st.close()
        return {"ok": True}
    if op != "attach":
        return {"ok": False, "error": f"unknown engine-stream op {op!r}"}
    if st is None:
        return {"ok": False, "error": f"no stream {sid} in this process"}
    cw = worker_mod._require_connected()
    writer = ChannelWriter(
        str(payload.get("chan", "")),
        cw.io,
        conn,
        store=cw.store,
        co_located=bool(payload.get("co")),
    )
    return st.attach(writer, conn)


# --------------------------------------------------------------- consumer


class TokenStream:
    """Consumer end of an engine token stream: iterate token-frame lists
    as the engine produces them.  Transport loss or a replica death
    raises :class:`EngineStreamError`; ``close()`` cancels an abandoned
    stream replica-side."""

    def __init__(self, cw, conn, reader, sid: int, timeout: float = 600.0):
        self._cw = cw
        self._conn = conn
        self._reader = reader
        self._sid = sid
        self._timeout = timeout
        self._finished = False

    def __iter__(self):
        from ray_tpu.dag.channel import ChannelBrokenError, ChannelClosedError

        try:
            while True:
                try:
                    is_err, frame = self._reader.get(timeout=self._timeout)
                except ChannelClosedError:
                    return
                except ChannelBrokenError as e:
                    raise EngineStreamError(
                        f"token stream broke mid-flight: {e}"
                    ) from e
                except TimeoutError as e:
                    raise EngineStreamError(
                        f"token stream stalled for {self._timeout}s"
                    ) from e
                if is_err:
                    raise EngineStreamError(str(frame))
                if frame.get("error"):
                    raise EngineStreamError(str(frame["error"]))
                toks = frame.get("t") or []
                if toks:
                    yield list(toks)
                if frame.get("done"):
                    self._finished = True
                    return
        finally:
            self.close()

    def close(self) -> None:
        conn, self._conn = self._conn, None
        if conn is None:
            return
        if not self._finished:
            # abandoned mid-stream: release the replica-side request
            try:
                self._cw.dag_rpc(
                    conn,
                    _engine_stream_msgtype(),
                    {"op": "cancel", "sid": self._sid},
                    5.0,
                )
            except Exception:  # noqa: BLE001 -- replica may already be gone
                pass
        try:
            self._reader.close()
        except Exception:  # noqa: BLE001 -- ring already reclaimed
            pass
        try:
            self._cw.close_dag_conn(conn)
        except RuntimeError:
            pass  # io loop already stopped


def _engine_stream_msgtype():
    from ray_tpu._private.protocol import MsgType

    return MsgType.ENGINE_STREAM


def open_token_stream(replica_handle, start_info: dict, timeout: float = 600.0) -> TokenStream:
    """Wire a dag-channel token stream to a replica for a stream the
    caller already started (``engine_stream_start`` returned
    ``start_info = {"sid", "node_id"}``).  Raises EngineStreamError when
    the transport can't be established — callers fall back to the pull
    path."""
    import os

    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.protocol import MsgType
    from ray_tpu.dag.channel import ChannelReader

    cw = worker_mod._require_connected()
    sid = int(start_info["sid"])
    aid = getattr(replica_handle, "_actor_id", b"") or b""
    try:
        reply = cw.request(MsgType.ACTOR_STATE, {"actor_id": aid})
    except Exception as e:
        raise EngineStreamError(f"cannot resolve replica: {e}") from e
    addr = reply.get("direct_addr")
    if not addr or reply.get("state") != "ALIVE":
        raise EngineStreamError(
            f"replica not streamable (state={reply.get('state')}, "
            f"direct_addr={addr!r})"
        )
    my_node = "" if cw.is_client else bytes(cw.node_id or b"").hex()
    co = (
        bool(my_node)
        and my_node == str(start_info.get("node_id") or "")
        and cw.store is not None
    )
    chan = f"eng:{bytes(aid).hex()[:12]}:{sid}:{os.getpid()}"
    reader = ChannelReader(chan, store=cw.store, co_located=co)

    def _on_push(payload):
        if payload.get("c") == chan:
            reader.push(payload)

    def _on_close():
        reader.wake_broken("replica connection lost")

    conn = cw.open_dag_conn(addr, on_push=_on_push, on_close=_on_close)
    try:
        ack = cw.dag_rpc(
            conn,
            MsgType.ENGINE_STREAM,
            {"op": "attach", "sid": sid, "chan": chan, "co": co},
            30.0,
        )
    except Exception as e:
        cw.close_dag_conn(conn)
        raise EngineStreamError(f"stream attach failed: {e}") from e
    if not ack.get("ok"):
        cw.close_dag_conn(conn)
        raise EngineStreamError(f"stream attach rejected: {ack.get('error')}")
    return TokenStream(cw, conn, reader, sid, timeout=timeout)
