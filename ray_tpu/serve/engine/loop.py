"""The resident decode loop: one engine thread per replica, one jitted
step per iteration.

Per PAPERS.md §2 (Pathways) the scarce resource on a single-controller
TPU runtime is per-step DISPATCH latency, so the engine is a loop that
lives inside the replica actor and whose host work per token step is
near zero: build four small int arrays, call ONE pre-compiled program
over the tp mesh (active-slot masking covers empty slots), read S int32s
back.  That device→host read is deliberate — it is the host-visible
token frontier that makes per-request TTFT/TPOT real measurements and
feeds every stream its next frame; batching it per step (not per
request) is what keeps the loop O(1) in concurrency.

Iteration shape (scheduler.py decides, this module executes):

    admit  →  [one prefill chunk]  →  [one decode step over the fleet]
           →  deliver frames  →  retire / recycle slots

Nothing here talks to the head: token frames leave through delivery
sinks (buffered result, or dag-channel streams via engine/transport.py)
and observability leaves through the serve tracer's batched SERVE_TRACE
frames plus ``ray_tpu_serve_engine_*`` gauges.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.exceptions import EngineStreamError
from ray_tpu.serve.engine.kv_cache import PagedKVCache
from ray_tpu.serve.engine.scheduler import (
    DECODE,
    EngineRequest,
    EngineScheduler,
)
from ray_tpu.tools import graftsan
from ray_tpu.util.lockwitness import named_lock, named_rlock

__all__ = ["EngineConfig", "InferenceEngine", "BufferSink"]


@dataclass(frozen=True)
class EngineConfig:
    """Engine geometry.  Every field here shapes a jitted program or a
    pool size — all of them are fixed at engine construction (the
    jit-shape invariant); only ``max_queue`` may be reconfigured live."""

    num_slots: int = 8  # concurrent sequences per replica
    page_size: int = 16  # tokens per KV page
    max_seq_len: int = 256  # per-sequence logical capacity (prompt + generated)
    # physical pool size; 0 = full residency (num_slots * pages_per_slot).
    # Undersize it to overcommit: admission then blocks on pool pressure
    num_pages: int = 0
    prefill_chunk: int = 32  # prompt tokens per prefill program call
    max_queue: int = 256  # bounded admission queue (overflow -> 503)
    max_new_tokens: int = 32  # default token budget per request
    # a consumer this many frames behind its stream is broken, not slow
    stream_outbox_limit: int = 4096
    gauge_period_s: float = 0.5

    @property
    def pages_per_slot(self) -> int:
        return max(1, math.ceil(self.max_seq_len / self.page_size))

    def pool_pages(self) -> int:
        return int(self.num_pages) or self.num_slots * self.pages_per_slot


class BufferSink:
    """Delivery sink for non-streaming callers: collect every token,
    fire done callbacks once, raise typed errors from ``result``."""

    def __init__(self):
        self.tokens: List[int] = []
        self.error: Optional[str] = None
        self.overloaded = False
        self._done = threading.Event()
        self._cbs: List[Any] = []
        self._lock = named_lock("BufferSink._lock")

    def emit(self, frame: dict) -> None:
        """Engine-thread only (single producer)."""
        self.tokens.extend(frame.get("t") or [])
        if frame.get("error"):
            self.error = str(frame["error"])
        if frame.get("done"):
            with self._lock:
                self._done.set()
                cbs, self._cbs = self._cbs, []
            for cb in cbs:
                cb(self)

    def add_done_callback(self, cb) -> None:
        with self._lock:
            if not self._done.is_set():
                self._cbs.append(cb)
                return
        cb(self)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("engine request did not complete in time")
        if self.error is not None:
            raise EngineStreamError(self.error)
        return list(self.tokens)


class InferenceEngine:
    """Continuous-batching engine over a tp-sharded paged LLM.

    ``llm`` is a ``ShardedLLM`` (serve/llm.py) — its ``engine_programs``
    builds the three jitted programs (pool init, prefill chunk, decode
    step) over the replica's mesh; everything else here is host-side.
    """

    def __init__(self, llm, config: Optional[EngineConfig] = None, deployment: str = "llm"):
        cfg = config or EngineConfig()
        if cfg.max_seq_len > llm.cfg.max_seq_len:
            raise ValueError(
                f"engine max_seq_len {cfg.max_seq_len} exceeds the model's "
                f"{llm.cfg.max_seq_len}"
            )
        self.cfg = cfg
        self.llm = llm
        self.deployment = deployment
        self._programs = llm.engine_programs(
            num_pages=cfg.pool_pages(), page_size=cfg.page_size
        )
        self._pages = self._programs["init"]()
        self.cache = PagedKVCache(
            cfg.num_slots, cfg.pages_per_slot, cfg.pool_pages(), cfg.page_size
        )
        self.sched = EngineScheduler(
            self.cache, max_queue=cfg.max_queue, prefill_chunk=cfg.prefill_chunk
        )
        self._lock = named_rlock("InferenceEngine._lock")
        # stream sinks with frames still queued for the wire: the ring is
        # finite, so streams longer than it need flush retries after the
        # consumer drains slots — the loop (and the idle tick) provide them
        self._laggards: set = set()
        # parked defrag requests, executed by the loop at iteration
        # boundaries (see defrag())
        self._defrag_reqs: List = []
        # staged weight hot-swap (update_weights): applied atomically at
        # the next iteration boundary so no prefill/decode program ever
        # sees a half-swapped tree
        self._pending_params = None
        self.weight_updates = 0
        self._wake = threading.Event()
        self._stop = False
        self._fatal: Optional[str] = None
        self._gauges = None
        self._last_gauges = 0.0
        self._tokens_reported = 0
        self.iterations = 0
        self._thread = threading.Thread(
            target=self._run, name=f"engine-{deployment}", daemon=True
        )
        self._thread.start()

    # -------------------------------------------------------------- intake

    def submit(
        self,
        prompt: List[int],
        max_new_tokens: Optional[int] = None,
        eos_token: Optional[int] = None,
        trace: Optional[dict] = None,
        sink=None,
    ) -> EngineRequest:
        """Enqueue one request.  Raises EngineOverloadedError on a full
        queue (the bounded failure mode), ValueError on capacity misuse,
        EngineStreamError after a fatal engine stop."""
        from ray_tpu.serve import tracing as serve_tracing

        serve_tracing.stamp(trace, "serve_engine_submit")
        with self._lock:
            # stop checked UNDER the lock: a submit racing the loop's
            # fatal teardown must either see _stop here or land in the
            # queue before fail_all drains it — never slip in after and
            # hang its caller on a queue nobody services
            if self._stop:
                raise EngineStreamError(self._fatal or "engine stopped")
            req = self.sched.submit(
                prompt,
                max_new_tokens if max_new_tokens is not None else self.cfg.max_new_tokens,
                eos_token=eos_token,
                trace=trace,
                sink=sink if sink is not None else BufferSink(),
            )
        # only an ACCEPTED request defers sealing to the engine — a
        # rejected one (overload/capacity) must still be sealed by the
        # submitting handler's finally, or its record would never ship
        serve_tracing.defer_finish(trace)
        self._wake.set()
        return req

    def cancel(self, req: EngineRequest) -> None:
        """Consumer abandoned the request: retire it at the next
        iteration boundary (mid-step cancel would desync the fleet)."""
        req.cancelled = True
        self._wake.set()

    # ------------------------------------------------------------ the loop

    @graftsan.loop_root
    def _run(self) -> None:
        # the resident loop is its own profiler role: sampled stacks from
        # this thread aggregate under "engine", not the host worker, so
        # `ray-tpu profile` separates decode-step time from actor-call
        # time on the same process (one dict write; no-op when the
        # profiler plane is hard-off)
        from ray_tpu._private import profiler

        profiler.set_thread_role("engine")
        try:
            while not self._stop:
                with self._lock:
                    busy = self.sched.has_work()
                if not busy:
                    self._run_defrags()
                    self._flush_laggards()
                    self._maybe_gauges()
                    fast = any(
                        getattr(s, "flushable", lambda: False)()
                        for s in self._laggards
                    )
                    self._wake.wait(0.002 if fast else 0.05)
                    self._wake.clear()
                    continue
                self._iteration()
        except BaseException as e:  # noqa: BLE001 -- a dead loop must fail every caller, typed
            self._fatal = f"engine loop died: {type(e).__name__}: {e}"
            import logging

            logging.getLogger(__name__).exception("inference engine loop died")
        finally:
            self._stop = True
            reason = self._fatal or "engine shut down"
            with self._lock:
                victims = self.sched.fail_all(reason)
                parked, self._defrag_reqs = self._defrag_reqs, []
            for req in victims:
                self._deliver(req, [], done=True, error=reason)
            for done, result in parked:  # never strand a defrag waiter
                result.update({"moves": 0, "error": reason})
                done.set()
            self._maybe_gauges(force=True)

    def update_weights(self, params=None, *, ref=None) -> None:
        """Stage a live weight hot-swap; applied at the next iteration
        boundary (decode never sees a half-swapped tree).

        ``params`` is a pytree matching ``llm.params`` OR a flat 1-D
        vector (``ravel_pytree`` order — what a trainer broadcasts through
        the device object tier).  ``ref`` is an ObjectRef to either form:
        resolving it here means a device-tier ref lands zero-copy when the
        trainer shares this process/mesh, and rides the collective pull
        plane cross-node — the host object path never re-serializes the
        checkpoint (core/DEVICE_TIER.md)."""
        if (params is None) == (ref is None):
            raise ValueError("update_weights wants exactly one of params=/ref=")
        if ref is not None:
            import ray_tpu

            params = ray_tpu.get(ref, timeout=300)
        import jax
        import jax.numpy as jnp

        if hasattr(params, "ndim") and getattr(params, "ndim") == 1:
            # flat vector → this model's own tree structure
            from jax.flatten_util import ravel_pytree

            _, unravel = ravel_pytree(self.llm.params)
            new = unravel(jnp.asarray(params))
        else:
            new = jax.tree.map(jnp.asarray, params)
        with self._lock:
            self._pending_params = new
        self._wake.set()

    def _apply_pending_params(self) -> None:
        with self._lock:
            new, self._pending_params = self._pending_params, None
        if new is None:
            return
        self.llm.params = new
        self.weight_updates += 1

    def _iteration(self) -> None:
        from ray_tpu.serve import tracing as serve_tracing

        self.iterations += 1
        self._apply_pending_params()
        self._run_defrags()
        with self._lock:
            self._reap_cancelled()
            admitted = self.sched.admit()
        for req in admitted:
            serve_tracing.stamp(req.trace, "serve_engine_admit")

        # -- one prefill chunk (chunked: decode never waits on a whole prompt)
        with self._lock:
            pf = self.sched.next_prefill()
        if pf is not None:
            self._prefill_chunk(*pf)

        # -- one decode step over the whole fleet: ONE program, any mix of
        # sequence lengths, inactive slots masked
        fleet = self.sched.decode_fleet()
        if fleet:
            self._decode_step(fleet)
        self._flush_laggards()
        self._maybe_gauges()

    def _reap_cancelled(self) -> None:
        """Lock held.  Retire cancelled running requests at the iteration
        boundary — and seal their (deferred) trace records: a cancelled
        request still happened."""
        from ray_tpu.serve import tracing as serve_tracing

        victims = [r for r in self.running_snapshot() if r.cancelled]
        for req in victims:
            self.sched.retire(req, error=None)
        victims += self.sched.drop_cancelled_queued()
        for req in victims:
            if req.trace is not None:
                req.trace["tokens"] = len(req.out)
            serve_tracing.finish_request(req.trace, error=False, final=True)
            self._deliver(req, [], done=True, error=None)

    def running_snapshot(self) -> List[EngineRequest]:
        return list(self.sched.running.values())

    def _prefill_chunk(self, req: EngineRequest, start: int, toks: List[int]) -> None:
        from ray_tpu.serve import tracing as serve_tracing

        if start == 0:
            serve_tracing.stamp(req.trace, "serve_prefill_start")
        C = self.cfg.prefill_chunk
        n_valid = len(toks)
        chunk = np.zeros(C, np.int32)
        chunk[:n_valid] = toks
        first, self._pages = self._programs["prefill"](
            self.llm.params,
            self._pages,
            np.ascontiguousarray(self.cache.tables[req.slot]),
            chunk,
            np.int32(start),
            np.int32(n_valid),
        )
        if not self.sched.note_prefill(req, n_valid):
            return
        # prompt fully resident: the chunk's sampled token IS the first
        # generated token, host-visible right here — the TTFT endpoint
        tok0 = int(first)
        serve_tracing.stamp(req.trace, "serve_first_token")
        req.state = DECODE
        with self._lock:
            finished = self.sched.note_token(req, tok0)
        if finished:
            self._retire(req, last_tokens=[tok0])
        else:
            self._deliver(req, [tok0])

    def _decode_step(self, fleet: List[EngineRequest]) -> None:
        S = self.cfg.num_slots
        tokens = np.zeros(S, np.int32)
        positions = np.zeros(S, np.int32)
        active = np.zeros(S, bool)
        for req in fleet:
            s = req.slot
            tokens[s] = req.out[-1]
            positions[s] = req.prompt_len + len(req.out) - 1
            active[s] = True
        nxt, self._pages = self._programs["decode"](
            self.llm.params,
            self._pages,
            np.ascontiguousarray(self.cache.tables),
            tokens,
            positions,
            active,
        )
        nxt = np.asarray(nxt)  # the per-step host sync: the token frontier
        for req in fleet:
            tok = int(nxt[req.slot])
            with self._lock:
                finished = self.sched.note_token(req, tok)
            if finished:
                self._retire(req, last_tokens=[tok])
            else:
                self._deliver(req, [tok])

    # ----------------------------------------------------------- delivery

    def _retire(self, req: EngineRequest, last_tokens: Optional[List[int]] = None) -> None:
        from ray_tpu.serve import tracing as serve_tracing

        serve_tracing.stamp(req.trace, "serve_decode_end")
        if req.trace is not None:
            req.trace["tokens"] = len(req.out)
        with self._lock:
            self.sched.retire(req)
        serve_tracing.finish_request(req.trace, error=False, final=True)
        self._deliver(req, last_tokens or [], done=True)

    def _deliver(
        self,
        req: EngineRequest,
        toks: List[int],
        done: bool = False,
        error: Optional[str] = None,
    ) -> None:
        from ray_tpu.serve import tracing as serve_tracing

        if error is not None:
            serve_tracing.stamp(req.trace, "serve_decode_end")
            serve_tracing.finish_request(req.trace, error=True, final=True)
        sink = req.sink
        if sink is None:
            return
        try:
            sink.emit({"t": toks, "done": bool(done), "error": error})
            if getattr(sink, "needs_flush", None) is not None and sink.needs_flush():
                self._laggards.add(sink)
        except Exception:  # noqa: BLE001 -- a broken consumer must not stall the fleet
            req.sink = None

    def _flush_laggards(self) -> None:
        """Re-flush streams whose channel ring was full at emit time —
        the consumer drains slots at its own pace, so delivery of a
        sequence longer than the ring depth completes here."""
        for sink in list(self._laggards):
            try:
                sink.flush()
                if not sink.needs_flush():
                    self._laggards.discard(sink)
            except Exception:  # noqa: BLE001 -- broken stream: its consumer sees the typed error
                self._laggards.discard(sink)

    # -------------------------------------------------------------- defrag

    def defrag(self, timeout: float = 30.0) -> Dict[str, Any]:
        """Compact the page pool: move allocated pages to the lowest
        physical ids and rewrite the page tables.  The device copy runs
        ON THE ENGINE THREAD at an iteration boundary — the loop runs
        jitted steps outside the lock with the pool buffers DONATED, so
        any other thread touching ``self._pages`` races a buffer that may
        already be consumed; this call just parks a request and waits."""
        done = threading.Event()
        result: Dict[str, Any] = {}
        with self._lock:
            if self._stop:
                raise EngineStreamError(self._fatal or "engine stopped")
            self._defrag_reqs.append((done, result))
        self._wake.set()
        if not done.wait(timeout):
            raise TimeoutError("defrag did not run within the timeout")
        return result

    def _run_defrags(self) -> None:
        """Engine thread, iteration boundary: the one place where nothing
        is mid-flight through a donated pages buffer."""
        with self._lock:
            reqs, self._defrag_reqs = self._defrag_reqs, []
        if not reqs:
            return
        with self._lock:
            moves = self.cache.compaction_plan()
            if moves:
                # one gather/scatter per buffer: every source page
                # materializes before any write, so overlapping src/dst
                # ranges are safe
                srcs = np.asarray([m[0] for m in moves], np.int32)
                dsts = np.asarray([m[1] for m in moves], np.int32)
                kp, vp = self._pages
                self._pages = (
                    kp.at[:, dsts].set(kp[:, srcs]),
                    vp.at[:, dsts].set(vp[:, srcs]),
                )
                self.cache.apply_compaction(moves)
            frag = self.cache.allocator.fragmentation()
        for done, result in reqs:
            result.update({"moves": len(moves), "fragmentation": frag})
            done.set()

    # ------------------------------------------------------------- observe

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = self.sched.stats()
            out.update(self.cache.stats())
        out["iterations"] = float(self.iterations)
        out.update({f"compile_{k}": v for k, v in self.compile_stats().items()})
        return out

    def compile_stats(self) -> Dict[str, int]:
        """Compiled-program cache sizes — the no-recompilation assertion
        surface: after warmup each stays at 1 no matter the length mix."""
        out = {}
        for name in ("prefill", "decode"):
            fn = self._programs[name]
            try:
                out[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 -- private jit API; absence degrades the stat only
                out[name] = -1
        return out

    def _maybe_gauges(self, force: bool = False) -> None:
        """Publish slot/page occupancy gauges at most every
        ``gauge_period_s`` (off the per-token path).  Outside a connected
        worker (unit tests drive the engine bare) this is a no-op."""
        now = time.monotonic()
        if not force and now - self._last_gauges < self.cfg.gauge_period_s:
            return
        self._last_gauges = now
        try:
            from ray_tpu._private import worker as worker_mod

            worker_mod._require_connected()
        except Exception:  # noqa: BLE001 -- bare engine: no metrics plane to publish to
            return
        try:
            g, c = self._ensure_gauges()
            st = self.stats()
            dep = {"deployment": self.deployment}
            g["slots"].set(st["slots_active"], {**dep, "kind": "active"})
            g["slots"].set(st["slots_decode"], {**dep, "kind": "decode"})
            g["slots"].set(st["slots_prefill"], {**dep, "kind": "prefill"})
            g["slots"].set(st["slots_total"], {**dep, "kind": "total"})
            g["queue"].set(st["queue_depth"], dep)
            g["pages"].set(st["pages_used"], {**dep, "kind": "used"})
            g["pages"].set(st["pages_total"], {**dep, "kind": "total"})
            g["frag"].set(st["fragmentation"], dep)
            delta = int(st["tokens_generated"]) - self._tokens_reported
            if delta > 0:
                c.inc(delta, dep)
                self._tokens_reported += delta
        except Exception:  # noqa: BLE001 -- observability is best-effort; serving already progressed
            import logging

            logging.getLogger(__name__).debug(
                "engine gauge publish failed", exc_info=True
            )

    def _ensure_gauges(self):
        if self._gauges is None:
            from ray_tpu.util.metrics import Counter, Gauge

            self._gauges = (
                {
                    "slots": Gauge(
                        "ray_tpu_serve_engine_slots",
                        "Engine slot occupancy by kind (active/prefill/decode/total)",
                        tag_keys=("deployment", "kind"),
                    ),
                    "queue": Gauge(
                        "ray_tpu_serve_engine_queue_depth",
                        "Requests waiting in the engine's bounded admission queue",
                        tag_keys=("deployment",),
                    ),
                    "pages": Gauge(
                        "ray_tpu_serve_engine_kv_pages",
                        "Paged KV cache pool occupancy (used/total pages)",
                        tag_keys=("deployment", "kind"),
                    ),
                    "frag": Gauge(
                        "ray_tpu_serve_engine_page_fragmentation",
                        "Free-list fragmentation of the KV page pool (0=contiguous)",
                        tag_keys=("deployment",),
                    ),
                },
                Counter(
                    "ray_tpu_serve_engine_tokens_total",
                    "Tokens generated by the continuous-batching engine",
                    tag_keys=("deployment",),
                ),
            )
        return self._gauges

    # ------------------------------------------------------------ teardown

    def reconfigure(self, max_queue: Optional[int] = None) -> None:
        """Live-adjustable knobs only (everything geometric is baked into
        compiled programs)."""
        if max_queue is not None:
            self.sched.max_queue = int(max_queue)

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout)
