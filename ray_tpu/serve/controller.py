"""ServeController: deployment reconciliation + autoscaling.

Analog of the reference's controller stack (reference:
python/ray/serve/controller.py:61 ServeController actor + control loop
:239; _private/deployment_state.py:958 DeploymentState replica FSM;
_private/autoscaling_policy.py:93 BasicAutoscalingPolicy).  Replicas are
plain actors; the controller reconciles target vs live counts and scales
on reported in-flight load.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


# the methods a draining replica refuses: exactly the ones whose CALLER
# retries a sibling on the typed rejection (stream_tokens' failover loop),
# so refusing them never drops a request.  Unary calls and generic
# streams already in the mailbox were routed BEFORE the handle learned of
# the drain (membership removal + the draining load flag stop new sends),
# so they run to retirement — zero dropped requests is the drain
# contract; the drain deadline bounds the stragglers.  Continuations
# (engine_stream_next/cancel), stats, and load probes must keep flowing
# or the drain protocol starves itself.
_ADMIT_METHODS = frozenset({"engine_stream_start"})

# Replica actor-name scheme.  This string format is a cross-layer
# contract: the head resolves `ray-tpu logs --replica deployment#index`
# by prefix-scanning its named-actor table for it (gcs/server.py
# _resolve_log_entity), and a recovered controller re-acquires living
# replicas the same way — change it in ONE place only.
REPLICA_NAME_PREFIX = "SERVE_REPLICA"


def replica_actor_name(deployment: str, gen: int = 0, rseq: int = 0) -> str:
    return f"{REPLICA_NAME_PREFIX}::{deployment}::{gen}::{rseq}"


def parse_replica_name(name: str) -> Optional[Dict[str, Any]]:
    """Inverse of :func:`replica_actor_name`; None for non-replica names."""
    parts = name.split("::")
    if len(parts) != 4 or parts[0] != REPLICA_NAME_PREFIX:
        return None
    try:
        return {"deployment": parts[1], "gen": int(parts[2]), "rseq": int(parts[3])}
    except ValueError:
        return None


class Replica:
    """Replica actor body: hosts the user callable."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None):
        import inspect

        if inspect.isclass(cls_or_fn):
            self.instance = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self.instance = cls_or_fn
        self.inflight = 0
        self.handled = 0
        self.draining = False
        self._streams: Dict[int, Any] = {}
        self._next_stream = 1
        if user_config is not None:
            self.reconfigure(user_config)

    async def handle_request(self, method: str, args, kwargs):
        # async: the worker hosts this actor on an asyncio loop, so batched
        # handlers (serve/batching.py futures) and overlapping requests work
        from ray_tpu.serve import tracing as serve_tracing

        # serve request tracing: the reserved kwarg is popped BEFORE the
        # user callable sees kwargs; replica-side stages (queue wait,
        # batch assembly, prefill/decode) stamp through the contextvar
        # scope.  None (recording off / old caller) costs one check.
        trace = kwargs.pop("_serve_trace", None)
        if self.draining and method in _ADMIT_METHODS:
            from ray_tpu.exceptions import ReplicaDrainingError

            raise ReplicaDrainingError(
                f"replica draining: new {method!r} work rejected"
            )
        serve_tracing.stamp(trace, "serve_replica_recv")
        self.inflight += 1
        err = False
        try:
            target = self.instance if method == "__call__" else getattr(self.instance, method)
            if method == "__call__" and not callable(target):
                raise TypeError("deployment instance is not callable")
            import inspect

            with serve_tracing.request_scope(trace):
                result = target(*args, **kwargs)
                if inspect.iscoroutine(result):
                    result = await result
            self.handled += 1
            return result
        except BaseException:
            err = True
            raise
        finally:
            self.inflight -= 1
            serve_tracing.finish_request(trace, error=err)

    async def handle_stream_start(self, method: str, args, kwargs):
        """Start a streaming call: the target returns a (sync or async)
        generator; chunks are pulled with handle_stream_next (reference:
        serve streaming responses / StreamingResponse — their proxy
        iterates the generator; here the HANDLE pulls batches so the
        stream flows through the normal actor-call path)."""
        import inspect

        self.inflight += 1
        try:
            target = (
                self.instance if method == "__call__" else getattr(self.instance, method)
            )
            gen = target(*args, **kwargs)
            if inspect.iscoroutine(gen):
                gen = await gen
        except BaseException:
            self.inflight -= 1  # a failed start must not pin the replica busy
            raise
        sid = self._next_stream
        self._next_stream += 1
        self._streams[sid] = gen
        return sid

    async def handle_stream_next(self, sid: int, max_chunks: int = 16):
        """Pull up to max_chunks items; returns (chunks, done).  Sync
        generators advance in an executor thread so a slow next() cannot
        stall the actor's event loop for other requests."""
        import asyncio
        import inspect

        gen = self._streams.get(sid)
        if gen is None:
            return [], True
        chunks = []
        done = False

        def _pull_sync():
            out = []
            try:
                for _ in range(max_chunks):
                    out.append(next(gen))
            except StopIteration:
                return out, True
            return out, False

        try:
            if inspect.isasyncgen(gen):
                try:
                    for _ in range(max_chunks):
                        chunks.append(await gen.__anext__())
                except StopAsyncIteration:
                    done = True
            else:
                chunks, done = await asyncio.get_running_loop().run_in_executor(
                    None, _pull_sync
                )
        except Exception:
            # only the actor still holding the stream releases the slot —
            # a concurrent cancel may have already popped it
            if self._streams.pop(sid, None) is not None:
                self.inflight -= 1
            raise
        if done:
            if self._streams.pop(sid, None) is not None:
                self.inflight -= 1
                self.handled += 1
        return chunks, done

    async def handle_stream_cancel(self, sid: int):
        """Abandoned stream (consumer broke out / timed out): drop the
        generator and release the inflight slot — phantom inflight would
        otherwise pin autoscaling up and wedge rolling-update drains.
        Async so generator cleanup (finally blocks releasing e.g. an LLM
        engine slot) runs properly on the actor's loop."""
        import inspect

        gen = self._streams.pop(sid, None)
        if gen is None:
            return False
        try:
            if inspect.isasyncgen(gen):
                await gen.aclose()
            else:
                gen.close()
        except Exception:
            pass  # racing __anext__ / user finally errors: slot still frees
        self.inflight -= 1
        return True

    def stats(self):
        return {"inflight": self.inflight, "handled": self.handled}

    def start_drain(self):
        """Enter the drain protocol (serve/FLEET.md): stop admitting new
        work, let in-flight requests and streams run to retirement.
        Idempotent; the controller's drainer polls drain_status until idle
        or the deadline."""
        self.draining = True
        return True

    def drain_status(self):
        """Is this replica safe to tear down?  Generic work is covered by
        inflight + the generator-stream table; engine deployments
        additionally expose engine_idle() (scheduler queue empty, no
        active slots, token-stream outboxes fully consumed)."""
        idle = self.inflight == 0 and not self._streams
        if idle and hasattr(self.instance, "engine_idle"):
            try:
                idle = bool(self.instance.engine_idle())
            except Exception:
                idle = False  # can't prove idle: keep draining
        return {"draining": self.draining, "inflight": self.inflight, "idle": idle}

    def load(self):
        """Cheap load snapshot for least-pressure routing: generic
        inflight plus engine pressure (queue depth, KV-page fraction)
        when the instance exposes engine_load().  Piggybacked onto the
        controller's routing publishes — handles never probe replicas."""
        out: Dict[str, Any] = {
            "inflight": float(self.inflight),
            "draining": bool(self.draining),
        }
        if hasattr(self.instance, "engine_load"):
            try:
                out.update(self.instance.engine_load())
            except Exception:
                pass  # engine mid-init: generic inflight still routes
        return out

    def reconfigure(self, user_config):
        """Apply a user_config IN PLACE — no restart (reference:
        serve/_private/replica.py reconfigure)."""
        if hasattr(self.instance, "reconfigure"):
            self.instance.reconfigure(user_config)
        return True

    def node_id(self) -> str:
        """Which node hosts this replica (locality-aware routing)."""
        import os

        return os.environ.get("RAY_TPU_NODE_ID", "")


class ServeController:
    """Detached actor: owns every deployment's goal state.

    Goal state is CHECKPOINTED to the head KV on every mutation and
    recovered on construction, so a controller crash/restart finds its
    deployments — and re-acquires the still-living replica actors by
    name — instead of losing everything (reference:
    python/ray/serve/controller.py:154 checkpoint,
    :305 _recover_config_from_checkpoint)."""

    CKPT_KEY = "serve:controller:ckpt"

    def __init__(self):
        self.deployments: Dict[str, dict] = {}
        self.version = 0
        self._fleet_m = None  # lazy util.metrics families (fleet plane)
        self._recover()
        # head fault tolerance: after this worker's CoreWorker reattaches
        # to a restarted head, re-sync replica state — probe every
        # replica, drop the dead, respawn to target, and re-publish so
        # handles refresh their (possibly stale) routing tables
        try:
            self._core().on_reattach(self._schedule_resync)
        except Exception:
            pass  # no runtime yet (unit-test construction): resync is moot
        # fleet plane: watchdog scale directives arrive on serve:fleet;
        # a poller thread piggybacks replica load snapshots onto routing
        # publishes (least-pressure routing needs a fleet-wide view the
        # per-client inflight counter can't give)
        try:
            self._subscribe_fleet()
            self._start_load_poller()
        except Exception:
            pass  # unit-test construction without a cluster

    def _schedule_resync(self):
        """Runs on the reattach-callback thread: route the resync through
        our OWN actor handle so it serializes with deploy/scale on the
        actor executor instead of mutating deployment state from a
        foreign thread mid-rolling-replace."""
        import ray_tpu
        from ray_tpu.serve.api import CONTROLLER_NAME

        try:
            me = ray_tpu.get_actor(CONTROLLER_NAME)
            me.resync_after_head_restart.remote()
        except Exception:  # noqa: BLE001
            logger.exception("post-restart serve resync could not be scheduled")

    def resync_after_head_restart(self):
        import ray_tpu

        changed = False
        for name, dep in list(self.deployments.items()):
            probes = [(r, r.stats.remote()) for r in list(dep["replicas"])]
            dead = []
            for r, ref in probes:
                try:
                    ray_tpu.get(ref, timeout=30)
                except Exception:
                    dead.append(r)
            for r in dead:
                try:
                    idx = dep["replicas"].index(r)
                except ValueError:
                    continue
                dep["replicas"].pop(idx)
                gone = dep["replica_names"].pop(idx)
                dep.get("replica_nodes", {}).pop(gone, None)
                changed = True
            before = len(dep["replicas"])
            self._reconcile(name)
            changed = changed or len(dep["replicas"]) != before
        # always republish: handles may hold replica handles whose actor
        # entries the restarted head reaped — a version bump makes them
        # re-pull instead of erroring against ghosts
        self.version += 1
        self._checkpoint()
        for name in self.deployments:
            self._publish_update(name)
        return changed

    # -------------------------------------------------- checkpoint/recover

    def _core(self):
        from ray_tpu._private import worker as worker_mod

        return worker_mod._require_connected()

    def _checkpoint(self):
        """Serialize every deployment's goal state (definition included,
        via the same serializer actors use) + live replica names."""
        import pickle

        from ray_tpu._private import serialization

        state = {}
        for name, d in self.deployments.items():
            state[name] = {
                "definition": serialization.serialize(
                    (d["cls"], d["init_args"], d["init_kwargs"])
                ).to_wire(),
                "target": d["target"],
                "actor_options": d["actor_options"],
                "route_prefix": d["route_prefix"],
                "autoscaling": d["autoscaling"],
                "max_concurrent_queries": d["max_concurrent_queries"],
                "def_version": d.get("def_version", ""),
                "user_config": d.get("user_config"),
                "gen": d.get("gen", 0),
                "rseq": d.get("rseq", 0),
                "replica_names": list(d.get("replica_names", [])),
            }
        try:
            self._core().kv_put(
                self.CKPT_KEY, pickle.dumps({"state": state, "version": self.version})
            )
        except Exception:
            pass  # a lost checkpoint degrades recovery, never serving

    def _recover(self):
        import pickle

        from ray_tpu._private.serialization import SerializedObject
        from ray_tpu._private import serialization

        try:
            blob = self._core().kv_get(self.CKPT_KEY)
        except Exception:
            return
        if not blob:
            return
        import ray_tpu

        data = pickle.loads(blob)
        self.version = data.get("version", 0)
        for name, s in data.get("state", {}).items():
            cls, init_args, init_kwargs = serialization.deserialize(
                SerializedObject.from_wire(s["definition"])
            )
            dep = {
                "name": name,
                "cls": cls,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "target": s["target"],
                "actor_options": s["actor_options"],
                "route_prefix": s["route_prefix"],
                "autoscaling": s["autoscaling"],
                "max_concurrent_queries": s["max_concurrent_queries"],
                "def_version": s.get("def_version", ""),
                "user_config": s.get("user_config"),
                "gen": s.get("gen", 0),
                "rseq": s.get("rseq", 0),
                "replicas": [],
                "replica_names": [],
            }
            self.deployments[name] = dep
            # re-acquire replicas that survived the controller: they are
            # NAMED actors, so the new controller finds them by name and
            # keeps serving without a cold start
            for rn in s.get("replica_names", []):
                try:
                    h = ray_tpu.get_actor(rn)
                except Exception:
                    continue
                dep["replicas"].append(h)
                dep["replica_names"].append(rn)
            self._reconcile(name)
        if self.deployments:
            self.version += 1
            for name in self.deployments:
                self._publish_update(name)
            self._checkpoint()

    def _publish_update(self, name: str):
        """Push the version bump to every handle (reference analog:
        LongPollHost notifying LongPollClients, _private/long_poll.py:184).
        Handles mark themselves stale and re-pull on their next request.
        Replica load snapshots piggyback on the same message — a handle
        absorbs them without an RPC, and load-only publishes (same
        version) never force a membership re-pull."""
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.protocol import MsgType

        message: Dict[str, Any] = {"version": self.version}
        dep = self.deployments.get(name)
        if dep is not None:
            message["replica_names"] = list(dep.get("replica_names", []))
            message["loads"] = dict(dep.get("replica_loads") or {})
        try:
            cw = worker_mod._require_connected()
            cw.request(
                MsgType.PUBLISH,
                {"channel": f"serve:{name}", "message": message},
            )
        except Exception:
            pass  # handles still converge via their pull path

    # ---------------------------------------------------------- fleet plane

    def _subscribe_fleet(self):
        """Scale directives from the head watchdog (gcs/server.py
        _apply_slo_scale) arrive on the serve:fleet channel.  The pubsub
        callback runs on the io thread and must not block, so it hands the
        directive to a short-lived thread that routes it through our OWN
        actor handle — same serialization rule as _schedule_resync: the
        directive mutates deployment state on the actor executor, never
        from a foreign thread."""
        import threading

        from ray_tpu._private import worker as worker_mod

        cw = worker_mod._require_connected()

        def _cb(msg):
            threading.Thread(
                target=self._dispatch_fleet_directive,
                args=(dict(msg or {}),),
                daemon=True,
            ).start()

        cw.subscribe("serve:fleet", _cb)

    def _dispatch_fleet_directive(self, directive: dict):
        import ray_tpu
        from ray_tpu.serve.api import CONTROLLER_NAME

        try:
            me = ray_tpu.get_actor(CONTROLLER_NAME)
            me.apply_fleet_directive.remote(directive)
        except Exception:  # noqa: BLE001
            logger.exception("fleet directive could not be scheduled")

    def apply_fleet_directive(self, directive: dict):
        """Apply ONE watchdog scale directive: scale_out adds a replica,
        scale_in removes one through the graceful drain protocol.  Bounds
        clamp HERE, not at the head — the controller owns goal state; the
        watchdog only expresses pressure.  Directives move one replica at
        a time: the watchdog's sustain/cooldown gating is the rate
        limiter, and single steps keep an overshooting burn estimate from
        doubling a fleet in one tick."""
        op = directive.get("op")
        name = directive.get("deployment")
        dep = self.deployments.get(name)
        if dep is None or op not in ("scale_out", "scale_in"):
            return False
        lo = max(1, int(directive.get("min_replicas", 1)))
        hi = max(lo, int(directive.get("max_replicas", 8)))
        cur = int(dep["target"])
        want = min(hi, cur + 1) if op == "scale_out" else max(lo, cur - 1)
        if want == cur:
            return False
        dep["target"] = want
        self._reconcile(name)
        self.version += 1
        self._checkpoint()
        self._publish_update(name)
        direction = "out" if op == "scale_out" else "in"
        try:
            m = self._fleet_metrics()
            m["scale_events_total"].inc(
                1.0, tags={"deployment": name, "direction": direction}
            )
            m["replicas"].set(float(len(dep["replicas"])), tags={"deployment": name})
        except Exception:
            pass
        self._fleet_event(
            f"serve fleet scale_{direction}: {name} {cur}->{want}",
            deployment=name,
            op=op,
            target=want,
            slo=str(directive.get("slo", "")),
        )
        return True

    def _fleet_metrics(self):
        """Lazy util.metrics families — the controller is a connected
        worker, so its series land in the head KV like any app metric and
        merge with the handle-side failover counters."""
        if self._fleet_m is None:
            from ray_tpu.util import metrics as metrics_mod

            self._fleet_m = {
                "replicas": metrics_mod.Gauge(
                    "ray_tpu_serve_fleet_replicas",
                    description="live replicas per serve deployment",
                    tag_keys=("deployment",),
                ),
                "scale_events_total": metrics_mod.Counter(
                    "ray_tpu_serve_fleet_scale_events_total",
                    description="fleet scale directives applied, by direction",
                    tag_keys=("deployment", "direction"),
                ),
                "failovers_total": metrics_mod.Counter(
                    "ray_tpu_serve_fleet_failovers_total",
                    description="mid-stream replica failovers (handle resubmits)",
                    tag_keys=("deployment",),
                ),
                "drained_total": metrics_mod.Counter(
                    "ray_tpu_serve_fleet_drained_total",
                    description="replicas retired on scale-in, by outcome",
                    tag_keys=("deployment", "outcome"),
                ),
            }
        return self._fleet_m

    def _init_fleet_metrics(self, name: str):
        """Zero-init every fleet family for a deployment so the scrape
        endpoint exposes all four the moment it exists (prom_validate
        gates on family presence; failovers increment from HANDLE
        processes, which may never run in this one)."""
        try:
            m = self._fleet_metrics()
            dep = self.deployments.get(name) or {}
            m["replicas"].set(
                float(len(dep.get("replicas", []))), tags={"deployment": name}
            )
            m["failovers_total"].inc(0.0, tags={"deployment": name})
            m["drained_total"].inc(0.0, tags={"deployment": name, "outcome": "clean"})
            for direction in ("out", "in"):
                m["scale_events_total"].inc(
                    0.0, tags={"deployment": name, "direction": direction}
                )
        except Exception:
            pass  # no cluster (unit test): metrics are moot

    def _fleet_event(self, message: str, **fields):
        """source=serve_fleet timeline event, fire-and-forget (same rule
        as chaos strikes: bookkeeping must not park the control path on a
        head that is mid-restart)."""
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.protocol import MsgType

        try:
            cw = worker_mod._require_connected()
        except Exception:
            return
        payload = {
            "severity": "INFO",
            "source": "serve_fleet",
            "message": message,
            "fields": fields,
        }

        async def _send():
            try:
                await cw.conn.send(MsgType.RECORD_EVENT, payload)
            except (ConnectionError, OSError):
                pass

        try:
            cw.io.spawn(_send())
        except Exception:  # graftlint: disable=silent-except -- event bookkeeping is best-effort; the state change already landed
            pass

    def _start_load_poller(self):
        import threading

        t = threading.Thread(
            target=self._load_poller_loop, daemon=True, name="serve-load-poller"
        )
        t.start()

    def _load_poller_loop(self):
        """Poll every replica's load() each serve_load_poll_period_s and
        piggyback the snapshots onto a same-version publish.  Runs on a
        daemon thread: reads take list() snapshots and writes publish
        REPLACEMENT dicts (the _resolve_replica_node rule), so the actor
        thread never sees a half-mutated view."""
        import time as _time

        import ray_tpu
        from ray_tpu._private.config import RayConfig

        while True:
            _time.sleep(max(0.1, float(RayConfig.serve_load_poll_period_s)))
            try:
                for name, dep in list(self.deployments.items()):
                    replicas = list(dep.get("replicas", []))
                    names = list(dep.get("replica_names", []))
                    if not replicas or len(replicas) != len(names):
                        continue  # mid-mutation snapshot: next tick
                    refs = []
                    for r, rn in zip(replicas, names):
                        try:
                            refs.append((rn, r.load.remote()))
                        except Exception:
                            continue
                    loads = {}
                    for rn, ref in refs:
                        try:
                            loads[rn] = ray_tpu.get(ref, timeout=5)
                        except Exception:
                            continue  # dead/wedged replica: unreported
                    dep["replica_loads"] = loads
                    self._publish_update(name)
                    try:
                        self._fleet_metrics()["replicas"].set(
                            float(len(replicas)), tags={"deployment": name}
                        )
                    except Exception:
                        pass
            except Exception:  # noqa: BLE001
                # a torn-down cluster mid-poll must not kill the thread
                # with a stack trace storm; next tick re-probes
                _time.sleep(1.0)

    def deploy(
        self,
        name: str,
        cls_or_fn,
        init_args,
        init_kwargs,
        num_replicas: int,
        ray_actor_options: Optional[dict],
        route_prefix: Optional[str],
        autoscaling_config: Optional[dict],
        max_concurrent_queries: int,
        def_version: str = "",
        user_config: Optional[dict] = None,
    ):
        import time as _time

        import ray_tpu

        dep = self.deployments.get(name)
        redeploy = False
        reconfigure = False
        if dep is None:
            dep = {
                "name": name,
                "replicas": [],
                "replica_names": [],
                "gen": 0,
                "rseq": 0,
                "route_prefix": route_prefix or f"/{name}",
                "max_concurrent_queries": max_concurrent_queries,
                "autoscaling": autoscaling_config,
            }
            self.deployments[name] = dep
        else:
            # version-gated rolling update ONLY when the definition changed
            # (caller-computed hash — the objects we hold are deserialized
            # copies, so identity checks are meaningless here); a plain
            # scale-up/down keeps warm replicas.  A user_config change
            # alone RECONFIGURES live replicas in place — no restart
            # (reference: deployment_state.py lightweight-update path)
            redeploy = bool(def_version) and dep.get("def_version") != def_version
            reconfigure = not redeploy and dep.get("user_config") != user_config
        dep["target"] = num_replicas
        dep["cls"] = cls_or_fn
        dep["init_args"] = init_args
        dep["init_kwargs"] = init_kwargs
        dep["actor_options"] = ray_actor_options or {}
        dep["max_concurrent_queries"] = max_concurrent_queries
        dep["def_version"] = def_version
        dep["user_config"] = user_config
        if route_prefix is not None:
            dep["route_prefix"] = route_prefix
        dep["autoscaling"] = autoscaling_config
        old = []
        if redeploy:
            old = self._rolling_replace(name)
        else:
            self._reconcile(name)
            if reconfigure and dep["replicas"]:
                # per-replica: one wedged replica must not leave the set
                # serving a silent old/new MIX — any replica that fails to
                # acknowledge is killed and respawned (the fresh replica
                # gets the new user_config at construction)
                refs = [
                    (r, r.reconfigure.remote(user_config)) for r in list(dep["replicas"])
                ]
                failed = []
                for r, ref in refs:
                    try:
                        ray_tpu.get(ref, timeout=60)
                    except Exception:
                        failed.append(r)
                for r in failed:
                    try:
                        idx = dep["replicas"].index(r)
                    except ValueError:
                        continue
                    dep["replicas"].pop(idx)
                    gone = dep["replica_names"].pop(idx)
                    dep.get("replica_nodes", {}).pop(gone, None)
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
                if failed:
                    self._reconcile(name)
        self.version += 1
        self._checkpoint()
        self._publish_update(name)
        self._init_fleet_metrics(name)
        if old:
            # retire the previous generation OFF the actor's call path: the
            # controller must keep serving get_handles (handles are
            # refreshing right now because of the publish above).  The
            # retirer waits out a cut-over grace, then drains in-flight
            # requests (bounded) before killing.
            import threading

            threading.Thread(
                target=self._retire_replicas, args=(old,), daemon=True
            ).start()
        return True

    def _retire_replicas(self, old: list):
        import time as _time

        import ray_tpu

        _time.sleep(1.0)  # publish propagation grace
        deadline = _time.time() + 30.0
        draining = list(old)
        from ray_tpu.exceptions import GetTimeoutError

        while draining and _time.time() < deadline:
            # submit all probes first so the waits overlap; judge each
            # per-replica: one crashed replica must not abort the drain for
            # the healthy ones, and a TIMEOUT means busy (a long handler
            # blocks stats) — exactly who needs the drain
            refs = [(r, r.stats.remote()) for r in draining]
            still = []
            for r, ref in refs:
                try:
                    s = ray_tpu.get(ref, timeout=10)
                except GetTimeoutError:
                    still.append(r)
                    continue
                except Exception:
                    continue  # actor dead: nothing to drain
                if s["inflight"] > 0:
                    still.append(r)
            draining = still
            if draining:
                _time.sleep(0.5)
        for victim in old:
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass

    def _spawn_replica(self, dep: dict):
        """Replicas are NAMED actors (SERVE_REPLICA::<dep>::<gen>::<seq>)
        so a recovered controller can re-acquire the living ones
        (reference analog: the reference's named replica actors,
        _private/deployment_state.py ReplicaName)."""
        import ray_tpu

        rname = replica_actor_name(
            dep["name"], dep.get("gen", 0), dep.get("rseq", 0)
        )
        dep["rseq"] = dep.get("rseq", 0) + 1
        actor_cls = ray_tpu.remote(Replica)
        opts = dict(dep["actor_options"])
        opts["name"] = rname
        handle = actor_cls.options(**opts).remote(
            dep["cls"], dep["init_args"], dep["init_kwargs"],
            user_config=dep.get("user_config"),
        )
        # resolve which node the replica landed on OFF the deploy path
        # (construction may be slow); handles use it for local-first
        # routing and converge via their pull fallback
        import threading

        threading.Thread(
            target=self._resolve_replica_node, args=(dep, rname, handle), daemon=True
        ).start()
        return handle, rname

    def _resolve_replica_node(self, dep: dict, rname: str, handle):
        import ray_tpu

        try:
            nid = ray_tpu.get(handle.node_id.remote(), timeout=300)
        except Exception:
            return
        # this runs on a daemon thread while the actor thread may iterate
        # dep['replica_nodes'] (_rolling_replace's comprehension, the
        # checkpoint walk): publish a REPLACEMENT dict instead of mutating
        # in place — dict assignment is atomic, iterators see old or new,
        # never "changed size during iteration"
        nodes = dict(dep.get("replica_nodes") or {})
        nodes[rname] = nid
        dep["replica_nodes"] = nodes

    def _rolling_replace(self, name: str) -> list:
        """Spin up the new generation, wait until it answers, swap it in,
        and RETURN the old replicas — the caller kills them only after the
        version publish (+grace), so handles never route to a dead set."""
        import ray_tpu

        dep = self.deployments[name]
        dep["gen"] = dep.get("gen", 0) + 1
        dep["rseq"] = 0
        spawned = [self._spawn_replica(dep) for _ in range(dep["target"])]
        fresh = [h for h, _ in spawned]
        try:
            ray_tpu.get([r.stats.remote() for r in fresh], timeout=120)
        except Exception:
            pass  # serve whatever came up; reconcile repairs stragglers
        old, dep["replicas"] = dep["replicas"], fresh
        dep["replica_names"] = [n for _, n in spawned]
        live = set(dep["replica_names"])
        dep["replica_nodes"] = {
            k: v for k, v in dep.get("replica_nodes", {}).items() if k in live
        }
        return old

    def _reconcile(self, name: str):
        dep = self.deployments[name]
        while len(dep["replicas"]) < dep["target"]:
            h, rname = self._spawn_replica(dep)
            dep["replicas"].append(h)
            dep["replica_names"].append(rname)
        victims = []
        while len(dep["replicas"]) > dep["target"]:
            # scale-in is GRACEFUL: the victim leaves the routing lists
            # now (the caller's publish stops new traffic), stops
            # admitting (start_drain), and a background drainer waits out
            # its in-flight work before teardown — zero dropped requests
            # on scale-in (serve/FLEET.md drain protocol)
            victim = dep["replicas"].pop()
            gone = dep["replica_names"].pop()
            dep.get("replica_nodes", {}).pop(gone, None)
            victims.append((victim, gone))
        if victims:
            self._drain_replicas(name, victims)

    def _drain_replicas(self, name: str, victims: list):
        import threading

        for victim, _ in victims:
            try:
                victim.start_drain.remote()
            except Exception:
                pass  # dead already: the drainer treats it as retired
        threading.Thread(
            target=self._drain_and_kill, args=(name, victims), daemon=True
        ).start()

    def _drain_and_kill(self, name: str, victims: list):
        """Background drainer: poll drain_status until every victim is
        idle or RayConfig.serve_drain_deadline_s elapses, then kill.  A
        victim that retires inside the window dies with nothing in
        flight (outcome=clean); deadline escalation is the bounded
        failure mode (outcome=deadline) — a wedged stream consumer must
        not pin chips forever."""
        import time as _time

        import ray_tpu
        from ray_tpu._private.config import RayConfig
        from ray_tpu.exceptions import GetTimeoutError

        deadline = _time.time() + float(RayConfig.serve_drain_deadline_s)
        pending = list(victims)
        outcomes = {rn: "deadline" for _, rn in victims}
        while pending and _time.time() < deadline:
            refs = [(v, rn, v.drain_status.remote()) for v, rn in pending]
            still = []
            for v, rn, ref in refs:
                try:
                    st = ray_tpu.get(ref, timeout=10)
                except GetTimeoutError:
                    still.append((v, rn))  # busy (a long handler blocks)
                    continue
                except Exception:
                    outcomes[rn] = "clean"  # already dead: nothing to drop
                    continue
                if st.get("idle"):
                    outcomes[rn] = "clean"
                else:
                    still.append((v, rn))
            pending = still
            if pending:
                _time.sleep(0.25)
        for victim, rn in victims:
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass
        for _, rn in victims:
            outcome = outcomes[rn]
            try:
                self._fleet_metrics()["drained_total"].inc(
                    1.0, tags={"deployment": name, "outcome": outcome}
                )
            except Exception:
                pass
            self._fleet_event(
                f"serve fleet drained replica {rn} ({outcome})",
                deployment=name,
                replica=rn,
                outcome=outcome,
            )

    def get_handles(self, name: str):
        dep = self.deployments.get(name)
        if dep is None:
            return None
        nodes = dep.get("replica_nodes", {})
        return {
            "replicas": dep["replicas"],
            # node hex per replica ("" while still resolving): handles
            # prefer same-node replicas (per-node proxy local-first path)
            "replica_nodes": [nodes.get(rn, "") for rn in dep["replica_names"]],
            "replica_names": list(dep["replica_names"]),
            # freshest load snapshots (the poller also pushes these over
            # pubsub between pulls — least-pressure routing inputs)
            "replica_loads": dict(dep.get("replica_loads") or {}),
            "max_concurrent_queries": dep["max_concurrent_queries"],
            "version": self.version,
        }

    def routes(self) -> Dict[str, str]:
        return {d["route_prefix"]: name for name, d in self.deployments.items()}

    def autoscale_tick(self):
        """One autoscaling pass: resize targets from reported in-flight
        load (reference: BasicAutoscalingPolicy.get_decision_num_replicas)."""
        import math

        import ray_tpu

        for name, dep in self.deployments.items():
            cfg = dep.get("autoscaling")
            if not cfg:
                continue
            try:
                stats = ray_tpu.get(
                    [r.stats.remote() for r in dep["replicas"]], timeout=5
                )
            except Exception:
                continue
            total_inflight = sum(s["inflight"] for s in stats)
            target_per = cfg.get("target_num_ongoing_requests_per_replica", 1)
            desired = math.ceil(total_inflight / max(target_per, 1e-9)) or cfg.get("min_replicas", 1)
            desired = max(cfg.get("min_replicas", 1), min(cfg.get("max_replicas", 8), desired))
            if desired != dep["target"]:
                dep["target"] = desired
                self._reconcile(name)
                self.version += 1
                self._checkpoint()
                self._publish_update(name)
        return self.version

    def delete_deployment(self, name: str):
        import ray_tpu

        dep = self.deployments.pop(name, None)
        if dep:
            for r in dep["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        self.version += 1
        self._checkpoint()
        self._publish_update(name)
        return True

    def list_deployments(self):
        return {
            name: {
                "num_replicas": len(d["replicas"]),
                "target": d["target"],
                "route_prefix": d["route_prefix"],
            }
            for name, d in self.deployments.items()
        }
