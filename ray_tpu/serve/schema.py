"""Declarative Serve config schema + apply.

Analog of the reference's serve schema/REST surface (reference:
python/ray/serve/schema.py ServeApplicationSchema — deployments declared
as data, applied idempotently; served over the dashboard REST API,
dashboard/modules/serve/).  Deployment callables are referenced by
``import_path`` ("pkg.module:attr"), so a config file fully describes an
application.
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class DeploymentSchema:
    name: str
    import_path: str  # "module.sub:attr" resolving to a @serve.deployment
    num_replicas: int = 1
    route_prefix: Optional[str] = None
    max_concurrent_queries: int = 100
    autoscaling_config: Optional[Dict[str, Any]] = None
    init_args: List[Any] = field(default_factory=list)
    # delivered to the instance's reconfigure(); a config that changes
    # ONLY this reconfigures live replicas in place, no restart
    # (reference: serve schema user_config + lightweight updates)
    user_config: Optional[Dict[str, Any]] = None
    # keys the config actually SET — apply() only overrides these, so a
    # decorator-declared route_prefix/num_replicas survives a config that
    # omits them
    present: frozenset = frozenset()

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DeploymentSchema":
        known = {f for f in DeploymentSchema.__dataclass_fields__} - {"present"}
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown deployment config keys: {sorted(extra)}")
        if "name" not in d or "import_path" not in d:
            raise ValueError("deployment config needs 'name' and 'import_path'")
        return DeploymentSchema(**d, present=frozenset(d))


@dataclass
class ServeApplicationSchema:
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeApplicationSchema":
        deps = d.get("deployments")
        if not isinstance(deps, list) or not deps:
            raise ValueError("config needs a non-empty 'deployments' list")
        return ServeApplicationSchema(
            deployments=[DeploymentSchema.from_dict(x) for x in deps]
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "deployments": [
                {
                    "name": s.name,
                    "import_path": s.import_path,
                    "num_replicas": s.num_replicas,
                    "route_prefix": s.route_prefix,
                    "max_concurrent_queries": s.max_concurrent_queries,
                    "autoscaling_config": s.autoscaling_config,
                    "init_args": s.init_args,
                    "user_config": s.user_config,
                }
                for s in self.deployments
            ]
        }


def _resolve_import_path(path: str):
    mod_name, _, attr = path.partition(":")
    if not attr:
        raise ValueError(f"import_path must be 'module:attr', got {path!r}")
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def apply(config: Dict[str, Any]) -> Dict[str, Any]:
    """Apply a declarative application config: every listed deployment is
    (re)deployed to its declared goal state (idempotent — the controller's
    version gate skips unchanged definitions)."""
    from ray_tpu import serve
    from ray_tpu.serve.api import Deployment

    schema = ServeApplicationSchema.from_dict(config)
    applied = []
    for d in schema.deployments:
        target = _resolve_import_path(d.import_path)
        opts = {"name": d.name}
        for key in (
            "num_replicas",
            "route_prefix",
            "max_concurrent_queries",
            "autoscaling_config",
            "user_config",
        ):
            if key in d.present:
                opts[key] = getattr(d, key)
        if isinstance(target, Deployment):
            dep = target.options(**opts)
        else:
            dep = serve.deployment(target, **opts)
        if "init_args" in d.present:
            dep = dep.bind(*d.init_args)
        elif isinstance(target, Deployment):
            dep = dep  # keep the decorator-bound args
        else:
            dep = dep.bind()
        serve.run(dep)
        applied.append(d.name)
    return {"applied": applied}


def status() -> Dict[str, Any]:
    """Current application state (reference: serve status REST)."""
    from ray_tpu import serve

    return {"deployments": serve.list_deployments()}
