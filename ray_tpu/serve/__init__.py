from ray_tpu.serve.api import (  # noqa: F401
    Deployment,
    autoscale_tick,
    delete,
    deployment,
    get_deployment_handle,
    list_deployments,
    proxy_addresses,
    run,
    shutdown,
    start_http_proxy,
)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve import engine  # noqa: F401  (continuous-batching engine)
from ray_tpu.serve import schema  # noqa: F401
