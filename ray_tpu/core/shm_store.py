"""Python client for the native shared-memory object store.

Analog of the reference's plasma client (reference:
src/ray/object_manager/plasma/client.cc) but with direct segment mapping
instead of a unix-socket protocol: every process mmaps the same tmpfs file
and calls into ``libray_tpu_store.so`` (src/object_store/store.cc) under a
process-shared robust mutex.  Sealed objects are immutable; ``get`` returns
zero-copy memoryviews into the mapping, pinned (refcounted) for as long as
any consumer view is alive via PEP-688 buffer-protocol exporters.

Object payload layout (one store object per framework object):
  u32 header_len | msgpack [metadata, inband_len, [buffer_lens]] |
  inband bytes | 64-pad | buffer0 | 64-pad | buffer1 | ...
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import struct
import sys
import traceback
from typing import List, Optional

import msgpack

logger = logging.getLogger(__name__)

from ray_tpu._private.build_native import ensure_lib
from ray_tpu._private.serialization import SerializedObject

_U32 = struct.Struct("<I")
_ALIGN = 64
# shared zero block for create_raw_sealed: full-length slices of bytes
# return the object itself, so only the final partial chunk ever copies
_ZERO_CHUNK = b"\x00" * (256 * 1024)


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class _Lib:
    _instance = None

    @classmethod
    def get(cls):
        if cls._instance is None:
            lib = ctypes.CDLL(ensure_lib("store"))
            lib.store_create.restype = ctypes.c_void_p
            lib.store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
            lib.store_attach.restype = ctypes.c_void_p
            lib.store_attach.argtypes = [ctypes.c_char_p]
            lib.store_detach.argtypes = [ctypes.c_void_p]
            lib.store_alloc.restype = ctypes.c_int
            lib.store_alloc.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.store_alloc_opts.restype = ctypes.c_int
            lib.store_alloc_opts.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.store_evict_candidates.restype = ctypes.c_int
            lib.store_evict_candidates.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.store_seal.restype = ctypes.c_int
            lib.store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.store_get.restype = ctypes.c_int
            lib.store_get.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            for name in (
                "store_release",
                "store_contains",
                "store_delete",
                "store_delete_if_unpinned",
                "store_abort",
            ):
                f = getattr(lib, name)
                f.restype = ctypes.c_int
                f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            for name in (
                "store_capacity",
                "store_used",
                "store_num_objects",
                "store_evictions",
                "store_mapped_size",
            ):
                f = getattr(lib, name)
                f.restype = ctypes.c_uint64
                f.argtypes = [ctypes.c_void_p]
            cls._instance = lib
        return cls._instance


# memoryview() only delegates to a Python-level __buffer__ from 3.12 on
# (PEP 688); before that, readers must fall back to copying under the pin
_MEMORYVIEW_DELEGATES = sys.version_info >= (3, 12)


class _PinnedRegion:
    """Buffer-protocol exporter that releases the store pin when collected.

    numpy arrays built over slices of ``memoryview(region)`` keep the region
    alive, so the pin (store refcount) outlives every zero-copy consumer —
    the moral equivalent of plasma's client-side release tracking
    (reference: plasma/client.cc Release).

    On Python < 3.12 ``memoryview(region)`` raises TypeError (PEP 688 is
    3.12+), so callers there read through ``region._view`` and COPY the
    bytes out while the region object — and therefore the pin — is still
    alive: correct on every version, zero-copy where the interpreter
    allows it.
    """

    def __init__(self, store: "ShmObjectStore", oid: bytes, view: memoryview):
        self._store = store
        self._oid = oid
        self._view = view

    def __buffer__(self, flags):
        return self._view.__buffer__(flags)

    def __del__(self):
        try:
            self._store.release(self._oid)
        except Exception:  # graftlint: disable=silent-except -- interpreter-teardown __del__; the segment may already be unmapped
            pass


class StoreFullError(MemoryError):
    """Allocation failed without eviction; the caller's spill hook (if any)
    should make room and retry."""


class ShmObjectStore:
    """One per process; head creates the segment, workers attach."""

    def __init__(self, path: str, capacity: int = 0, create: bool = False, nslots: int = 65536):
        self._lib = _Lib.get()
        self._path = path
        # optional hook: called with (bytes_needed) under memory pressure;
        # returns True if room was made (spill-to-disk orchestration —
        # reference analog: LocalObjectManager::SpillObjects triggered
        # before eviction of referenced data, raylet/local_object_manager.h)
        self.spill_hook = None
        # optional (event_type, payload) callback for cluster-event
        # reporting (wired by the raylet to the head's event ring)
        self.event_hook = None
        self._last_pressure_report = float("-inf")
        if create:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            self._handle = self._lib.store_create(path.encode(), capacity, nslots)
        else:
            self._handle = self._lib.store_attach(path.encode())
        if not self._handle:
            raise OSError(f"cannot {'create' if create else 'attach'} shm store at {path}")
        size = self._lib.store_mapped_size(self._handle)
        fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._mv = memoryview(self._mm)

    ID_LEN = 28  # must match kIdLen in src/object_store/store.cc

    def _check(self, object_id: bytes):
        if self._handle is None:
            raise OSError("shm store is closed")
        if len(object_id) != self.ID_LEN:
            raise ValueError(f"object id must be {self.ID_LEN} bytes, got {len(object_id)}")

    # -- framework-object API -------------------------------------------------

    def put_serialized(self, object_id: bytes, obj: SerializedObject) -> bool:
        """Write + seal. Returns False if the object already exists."""
        self._check(object_id)
        header = msgpack.packb(
            [obj.metadata, len(obj.inband), [b.nbytes for b in obj.buffers]],
            use_bin_type=True,
        )
        prefix = _U32.size + len(header) + len(obj.inband)
        total = _pad(prefix)
        for b in obj.buffers:
            total += _pad(b.nbytes)
        off = ctypes.c_uint64()
        rc = self._alloc_with_spill(object_id, total, ctypes.byref(off))
        if rc == -1:
            return False
        if rc != 0:
            raise MemoryError(
                f"shm store cannot fit object of {total} bytes "
                f"(used {self.used()}/{self.capacity()})"
            )
        base = off.value
        try:
            view = self._mv[base : base + total]
            pos = 0
            view[pos : pos + _U32.size] = _U32.pack(len(header))
            pos += _U32.size
            view[pos : pos + len(header)] = header
            pos += len(header)
            if obj.inband:
                view[pos : pos + len(obj.inband)] = obj.inband
            pos = _pad(pos + len(obj.inband))
            for b in obj.buffers:
                if b.nbytes:
                    if b.format == "B" and b.ndim == 1:
                        flat = b
                    else:
                        try:
                            flat = b.cast("B")  # zero-copy for contiguous views
                        except TypeError:
                            flat = memoryview(bytes(b))
                    view[pos : pos + b.nbytes] = flat
                pos = _pad(pos + b.nbytes)
            del view
        except BaseException:
            # roll back the unsealed allocation so the id isn't wedged forever
            self._lib.store_abort(self._handle, object_id)
            raise
        if self._lib.store_seal(self._handle, object_id) != 0:
            # the only way an ALLOCATED slot stops being sealable is a
            # concurrent store_delete (it tombstones regardless of the
            # creator pin): the owner's last reference died while we were
            # writing, so the value is unreachable by contract — degrade
            # to a no-op rather than failing the producing task (seen as
            # actor creations poisoned by their own dropped creation ref)
            self._lib.store_abort(self._handle, object_id)
            return False
        self._lib.store_release(self._handle, object_id)  # drop creator pin
        return True

    def get_serialized(self, object_id: bytes) -> Optional[SerializedObject]:
        """Zero-copy read of a sealed object; None if absent/unsealed."""
        self._check(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, object_id, ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        region = _PinnedRegion(self, object_id, self._mv[off.value : off.value + size.value])
        if _MEMORYVIEW_DELEGATES:
            view = memoryview(region)  # slices keep `region` (the pin) alive
            copy_out = False
        else:
            view = region._view  # `region` local holds the pin while we read
            copy_out = True
        (hlen,) = _U32.unpack(view[: _U32.size])
        pos = _U32.size
        metadata, inband_len, buf_lens = msgpack.unpackb(
            bytes(view[pos : pos + hlen]), raw=False
        )
        pos += hlen
        inband = bytes(view[pos : pos + inband_len])
        pos = _pad(pos + inband_len)
        buffers: List[memoryview] = []
        for blen in buf_lens:
            chunk = view[pos : pos + blen]
            buffers.append(memoryview(bytes(chunk)) if copy_out else chunk)
            pos = _pad(pos + blen)
        sobj = SerializedObject(bytes(metadata), inband, buffers)
        if copy_out:
            # pre-3.12 buffers are copies, but the pin contract must not be
            # version-dependent: a live get_serialized() result keeps the
            # object evict-exempt either way (test_pinned_not_evicted)
            sobj._pin = region
        return sobj

    def metadata_of(self, object_id: bytes) -> Optional[bytes]:
        """Metadata tag of a sealed object without materializing inband or
        buffers — a cheap tier probe (e.g. META_DEVICE envelopes written by
        the device-store eviction ladder, core/DEVICE_TIER.md).  None if
        absent/unsealed."""
        self._check(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, object_id, ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        try:
            view = self._mv[off.value : off.value + size.value]
            (hlen,) = _U32.unpack(view[: _U32.size])
            metadata, _, _ = msgpack.unpackb(
                bytes(view[_U32.size : _U32.size + hlen]), raw=False
            )
            return bytes(metadata)
        finally:
            self._lib.store_release(self._handle, object_id)

    # -- raw ops (object-transfer layer) --------------------------------------

    def raw_view(self, object_id: bytes) -> Optional[memoryview]:
        """Pinned zero-copy view of a sealed object's full store value (the
        serialized wire image).  The pin is released when the view's owner
        (_PinnedRegion) is garbage collected.  Used by the transfer agent to
        stream an object to another node byte-for-byte."""
        self._check(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, object_id, ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        region = _PinnedRegion(self, object_id, self._mv[off.value : off.value + size.value])
        if _MEMORYVIEW_DELEGATES:
            return memoryview(region)
        # pre-3.12: copy the wire image out under the pin (`region` lives
        # until after bytes() completes), then let the pin drop
        return memoryview(bytes(region._view))

    def raw_create(self, object_id: bytes, size: int) -> Optional[memoryview]:
        """Allocate an unsealed object of `size` bytes and return a writable
        view; None if the id already exists.  Pair with raw_seal/raw_abort.
        This is the receive half of a chunked pull (analog: reference
        ObjectBufferPool create-chunk path, object_manager/object_buffer_pool.h)."""
        self._check(object_id)
        off = ctypes.c_uint64()
        rc = self._alloc_with_spill(object_id, size, ctypes.byref(off))
        if rc == -1:
            return None
        if rc != 0:
            raise MemoryError(
                f"shm store cannot fit object of {size} bytes "
                f"(used {self.used()}/{self.capacity()})"
            )
        return self._mv[off.value : off.value + size]

    def _alloc_with_spill(self, object_id: bytes, size: int, off_ref) -> int:
        """Allocate, preferring spill-to-disk over LRU eviction when a
        spill hook is wired: in-scope objects must not be silently dropped
        to make room (they'd need lineage reconstruction to come back)."""
        if self.spill_hook is None:
            return self._lib.store_alloc(self._handle, object_id, size, off_ref)
        if size + _ALIGN > self.capacity():
            # can never fit even after padding: fail without churning the
            # working set to disk
            return -2
        for _ in range(3):
            rc = self._lib.store_alloc_opts(self._handle, object_id, size, 0, off_ref)
            if rc != -2:
                return rc
            try:
                made_room = self.spill_hook(size)
            except Exception:  # noqa: BLE001
                # a broken spill hook must not fail the alloc (the evicting
                # fallback below still runs) — but it must not be invisible
                traceback.print_exc(file=sys.stderr)
                made_room = False
            if not made_room:
                break
        # last resort: evicting alloc (out-of-scope data goes first by LRU).
        # This is the outcome spill-before-evict exists to prevent — loudly
        # record that in-scope objects may now be LRU-dropped (a put()
        # object without lineage lost here is unrecoverable), so a slow or
        # full spill disk under sustained pressure is diagnosable.  Rate-
        # limited: sustained pressure means this path fires per-alloc, and
        # an unthrottled warning+event per alloc would flood the log and
        # the head's event ring with the very condition being reported.
        import time as _time

        now = _time.monotonic()
        if now - self._last_pressure_report > 10.0:
            self._last_pressure_report = now
            logger.warning(
                "shm store: spill could not make room for %d bytes after 3 "
                "rounds (used %d/%d); falling back to LRU eviction — in-scope "
                "objects without lineage may be lost",
                size,
                self.used(),
                self.capacity(),
            )
            if self.event_hook is not None:
                try:
                    self.event_hook(
                        "OBJECT_STORE_EVICTING_FALLBACK",
                        {
                            "requested": size,
                            "used": self.used(),
                            "capacity": self.capacity(),
                        },
                    )
                except Exception:  # graftlint: disable=silent-except -- pressure-event emission is best-effort; the alloc itself must proceed
                    pass
        return self._lib.store_alloc(self._handle, object_id, size, off_ref)

    def evict_candidates(self, max_n: int = 64) -> List[tuple]:
        """LRU-first (object_id, size) pairs that are sealed and unpinned —
        what a spill pass would move to disk."""
        if not self._handle:
            return []
        ids = ctypes.create_string_buffer(max_n * self.ID_LEN)
        sizes = (ctypes.c_uint64 * max_n)()
        n = self._lib.store_evict_candidates(self._handle, max_n, ids, sizes)
        out = []
        for i in range(max(0, n)):
            out.append((ids.raw[i * self.ID_LEN : (i + 1) * self.ID_LEN], int(sizes[i])))
        return out

    def create_raw_sealed(self, object_id: bytes, size: int, init: bytes = b"") -> bool:
        """Allocate a zero-initialized `size`-byte object, write ``init`` at
        offset 0, and seal it in one step — the backing region for a
        compiled-DAG channel ring (dag/channel.py), which both endpoints
        mutate in place through pinned views for the channel's lifetime.
        ``init`` lands BEFORE the seal, so a peer that attaches the moment
        the object becomes visible can never observe a half-initialized
        header.  The pins the endpoints take keep the region off the LRU.
        Returns False if the id already exists."""
        view = self.raw_create(object_id, size)
        if view is None:
            return False
        # zero in bounded chunks: one `b"\x00" * size` temporary would
        # transiently double a multi-MB ring's footprint per channel
        off = 0
        while off < size:
            n = min(size - off, len(_ZERO_CHUNK))
            view[off : off + n] = _ZERO_CHUNK[:n]
            off += n
        if init:
            view[: len(init)] = init
        self.raw_seal(object_id)
        return True

    def pinned_view(self, object_id: bytes):
        """Writable zero-copy view of a sealed object plus the pin holder:
        ``(view, region)`` or None if absent.  The caller must keep
        ``region`` alive for as long as it touches ``view`` — dropping the
        last reference releases the store pin (on every Python version;
        this bypasses the PEP-688 read path, so pre-3.12 gets zero-copy
        too).  Mutating the view is only sound for regions whose layout is
        owned by cooperating endpoints (DAG channel rings) — sealed data
        objects stay immutable by contract."""
        self._check(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, object_id, ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        region = _PinnedRegion(self, object_id, self._mv[off.value : off.value + size.value])
        return region._view, region

    def raw_seal(self, object_id: bytes):
        if self._lib.store_seal(self._handle, object_id) != 0:
            self._lib.store_abort(self._handle, object_id)
            raise RuntimeError("seal failed")
        self._lib.store_release(self._handle, object_id)  # drop creator pin

    def raw_abort(self, object_id: bytes):
        self._lib.store_abort(self._handle, object_id)

    def contains(self, object_id: bytes) -> bool:
        if not self._handle:
            return False
        return bool(self._lib.store_contains(self._handle, object_id))

    def release(self, object_id: bytes):
        if self._handle:
            self._lib.store_release(self._handle, object_id)

    def delete(self, object_id: bytes):
        if self._handle:
            self._lib.store_delete(self._handle, object_id)

    def delete_if_unpinned(self, object_id: bytes) -> bool:
        """Delete unless a reader pins it (spill path safety); True if the
        shm copy is gone."""
        if not self._handle:
            return False
        return self._lib.store_delete_if_unpinned(self._handle, object_id) == 0

    def capacity(self) -> int:
        return self._lib.store_capacity(self._handle) if self._handle else 0

    def used(self) -> int:
        return self._lib.store_used(self._handle) if self._handle else 0

    def num_objects(self) -> int:
        return self._lib.store_num_objects(self._handle) if self._handle else 0

    def evictions(self) -> int:
        return self._lib.store_evictions(self._handle) if self._handle else 0

    def close(self):
        """Detach.  If zero-copy views are still alive we must NOT unmap the
        segment under them — leave the mapping to the process teardown."""
        if self._handle:
            handle, self._handle = self._handle, None
            try:
                self._mv.release()
                self._mm.close()
            except BufferError:
                # outstanding exported views: skip munmap, only free the
                # client bookkeeping at exit (the OS reclaims the mapping)
                return
            self._lib.store_detach(handle)
