"""Worker process main: execute tasks pushed by the head.

Analog of the reference's default_worker.py + the C++ task execution loop
(reference: python/ray/_private/workers/default_worker.py,
src/ray/core_worker/core_worker.cc RunTaskExecutionLoop:2176 /
ExecuteTask:2231, and the Cython execute_task upcall _raylet.pyx:596).

A worker is either a pool worker (runs one normal task at a time) or an
actor-dedicated worker (holds the instance; executes its method calls in
submission order, or concurrently up to max_concurrency, or on an asyncio
loop for async actors — the analog of reference concurrency groups /
fiber-based async actors, src/ray/core_worker/transport/
concurrency_group_manager.cc + fiber.h).
"""

from __future__ import annotations

import inspect
import os
import queue
import sys
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu._private import log_plane, serialization
from ray_tpu._private.ids import JobID
from ray_tpu._private.task_spec import ACTOR_CREATION_TASK, ACTOR_TASK, NORMAL_TASK, TaskSpec
from ray_tpu.exceptions import RayTaskError
from ray_tpu.util.lockwitness import named_condition, named_lock


class _ActorState:
    def __init__(self):
        self.instance: Any = None
        self.cls: Any = None
        self.async_loop: Optional[Any] = None  # asyncio loop for async actors
        self.executor: Optional[ThreadPoolExecutor] = None
        # creation spec wire, kept for the head-FT reattach announce (a
        # restarted head re-learns this worker hosts the actor)
        self.spec_wire: Optional[dict] = None


class WorkerRuntime:
    def __init__(self, core_worker):
        self.cw = core_worker
        self.actor = _ActorState()
        self.task_queue: "queue.Queue[dict]" = queue.Queue()
        self.cancelled: set = set()
        self._concurrency_sem: Optional[threading.Semaphore] = None
        self._direct_server = None
        self._direct_port = 0
        # mutual exclusion between eager actor calls and compiled-DAG
        # executor steps (ray_tpu/dag/): a sequential actor keeps its
        # one-call-at-a-time contract across both modes
        self.actor_lock = named_lock("WorkerRuntime.actor_lock")
        # lease fast path (control plane): batched completion frames per
        # holder conn + batched flight records to the head.  Flushing is
        # an io-loop TIMER (~2ms coalescing window), never the run
        # thread: a completed result must reach the holder even while the
        # NEXT task blocks in user code or arg resolution — holding it
        # until the queue drains deadlocks consumer tasks waiting on the
        # unflushed result.
        self._lease_out_lock = named_lock("WorkerRuntime._lease_out_lock")
        self._lease_outbox: Dict[int, list] = {}  # id(conn) -> results
        self._lease_conns: Dict[int, Any] = {}
        self._stats_buffer: List[dict] = []
        self._lease_flush_armed = False
        # calls between dequeue and their TASK_DONE flush: the actor_lock
        # covers only user code, so the preemption fence must ALSO wait
        # for this to reach zero — a call whose completion report is
        # still in flight when the checkpoint ships would be requeued by
        # the head and double-executed on the restored state
        self._inflight = 0
        self._inflight_cv = named_condition("WorkerRuntime._inflight_cv")
        self._dag_runtime = None  # lazy: ray_tpu.dag.executor.DagWorkerRuntime
        # per-caller sequential ordering across the head→direct transition
        # (reference analog: sequential_actor_submit_queue.cc): seq we expect
        # next per caller_id, plus held-back out-of-order specs
        self._expected_seq: Dict[bytes, int] = {}
        self._held: Dict[bytes, Dict[int, dict]] = {}
        # head-pushed tasks between push and their TASK_DONE flush, spec
        # wire by task id: re-announced on a head-FT reattach so the
        # restarted head re-owns them instead of treating the driver's
        # idempotent resubmit as fresh work (double execution).  Locked:
        # io thread inserts, executor threads retire, the reattach
        # coroutine snapshots — an unlocked snapshot can raise mid-announce
        # and leave the actor un-re-announced (ghost-reaped while alive)
        self._head_inflight: Dict[bytes, dict] = {}
        self._head_inflight_lock = named_lock("WorkerRuntime._head_inflight_lock")

    # ------------------------------------------------------------ main loop

    def run(self):
        """Pull pushed tasks off the queue and execute (the analog of
        RunTaskExecutionLoop)."""
        while True:
            payload = self.task_queue.get()
            if payload is None:
                break
            if "cancel" in payload:
                self.cancelled.add(payload["cancel"])
                continue
            if "flush_held" in payload:
                for s, r in self._flush_expired(payload["flush_held"]):
                    self._execute_guarded(s, r)
                continue
            spec = TaskSpec.from_wire(payload["spec"])
            reply_to = payload.get("direct")
            if payload.get("lease") is not None:
                # lease-pushed normal task: execute serially (one lease =
                # one concurrent task of shape S); completions flush on
                # the io-loop timer armed by _queue_lease_result
                self._execute_guarded(spec, ("lease", payload["lease"]))
                continue
            if spec.task_type == ACTOR_TASK and self._concurrency_sem is None:
                # sequential actor: enforce per-caller seq order so calls
                # that raced the head→direct routing transition still run
                # in submission order
                for s, r in self._sequence(spec, reply_to):
                    self._execute_guarded(s, r)
                continue
            if spec.task_type == ACTOR_TASK and self._concurrency_sem is not None:
                # concurrent actor: run in the pool, keep pulling
                self.actor.executor.submit(self._execute_guarded, spec, reply_to)
            else:
                self._execute_guarded(spec, reply_to)

    def _sequence(self, spec: TaskSpec, reply_to):
        """Yield (spec, reply) pairs now runnable under per-caller seq
        order; hold out-of-order arrivals (bounded wait, then run anyway —
        at-least-once retry semantics make duplicates possible)."""
        import time as _time

        from ray_tpu._private.config import RayConfig

        caller = spec.caller_id or b""
        if caller not in self._expected_seq and spec.seq_no == 0:
            self._expected_seq[caller] = 0  # genuine first call
        if caller not in self._expected_seq or spec.seq_no > self._expected_seq[caller]:
            # Out of order, or first contact at seq>0 — the caller's earlier
            # calls may still be in flight on the head path (a direct frame
            # can win that race), or we're a restarted worker mid-stream.
            # Hold; the flush timer runs it anyway if no predecessor shows
            # (a gap may never fill, e.g. predecessor died with the old
            # worker).
            held = self._held.setdefault(caller, {})
            held[spec.seq_no] = {"reply": reply_to, "spec": spec, "t": _time.time()}
            limit = RayConfig.direct_call_reorder_wait_s
            threading.Timer(
                limit + 0.05, lambda: self.task_queue.put({"flush_held": caller})
            ).start()
            return
        self._expected_seq[caller] = max(self._expected_seq[caller], spec.seq_no + 1)
        yield spec, reply_to
        held = self._held.get(caller, {})
        while self._expected_seq[caller] in held:
            nxt = held.pop(self._expected_seq[caller])
            self._expected_seq[caller] += 1
            yield nxt["spec"], nxt["reply"]

    def _flush_expired(self, caller: bytes):
        """Run held-back out-of-order calls whose wait expired (in seq
        order), advancing expected past them."""
        import time as _time

        from ray_tpu._private.config import RayConfig

        held = self._held.get(caller, {})
        limit = RayConfig.direct_call_reorder_wait_s
        now = _time.time()
        for s in sorted(held):
            if now - held[s]["t"] >= limit or s <= self._expected_seq.get(caller, 0):
                h = held.pop(s)
                self._expected_seq[caller] = max(self._expected_seq.get(caller, 0), s + 1)
                yield h["spec"], h["reply"]

    def on_push(self, payload: dict):
        """Called from the io thread; never block it."""
        if payload.get("directive"):
            return  # spawn directives are raylet business, not ours
        wire = payload.get("spec")
        if (
            wire is not None
            and "direct" not in payload
            and "lease" not in payload
        ):
            # head-path task: tracked from PUSH (a queued-but-unstarted
            # task must also be re-announced after a head restart, or the
            # driver's resubmit would race this copy — double execution)
            tid = bytes(wire.get("task_id") or b"")
            if tid:
                with self._head_inflight_lock:
                    self._head_inflight[tid] = wire
        self.task_queue.put(payload)

    def reattach_state(self) -> dict:
        """Head-FT reattach announce (core_worker calls this on redial):
        the hosted actor (if any) + every head-path task still owed a
        TASK_DONE."""
        out: Dict[str, Any] = {}
        if self.actor.instance is not None and self.actor.spec_wire is not None:
            out["actor"] = self.actor.spec_wire
            if self._direct_port:
                out["actor_direct_addr"] = f"0.0.0.0:{self._direct_port}"
        with self._head_inflight_lock:
            out["running"] = list(self._head_inflight.values())
        return out

    # --------------------------------------- lease fast path (batched IO)

    # coalescing window for completion/stats frames: everything that
    # finishes within it rides one frame, and a result is never held
    # hostage by the NEXT task's execution
    _LEASE_FLUSH_WINDOW_S = 0.002

    def _queue_lease_result(self, conn, spec: TaskSpec, inline, sealed, ph):
        """Accumulate one lease-task completion for the holder + one
        flight record for the head, and arm the io-loop flush timer."""
        import time as _time

        cid = id(conn)
        with self._lease_out_lock:
            self._lease_conns[cid] = conn
            self._lease_outbox.setdefault(cid, []).append(
                {"task_id": spec.task_id, "inline": inline, "stored": sealed}
            )
            if ph is not None:
                ph.setdefault("done", _time.time())
                self._stats_buffer.append(
                    {
                        "task_id": spec.task_id,
                        "name": spec.function_name or spec.method_name or "task",
                        "granted_by": getattr(spec, "granted_by", "cached_lease"),
                        "phases": ph,
                        "pid": os.getpid(),
                    }
                )
            if self._lease_flush_armed:
                return
            self._lease_flush_armed = True

        async def _later():
            import asyncio

            await asyncio.sleep(self._LEASE_FLUSH_WINDOW_S)
            with self._lease_out_lock:
                self._lease_flush_armed = False
            self._flush_lease_batches()

        try:
            self.cw.io.spawn(_later())
        except Exception:  # graftlint: disable=silent-except -- io loop gone (shutdown); the inline flush below is the recovery
            with self._lease_out_lock:
                self._lease_flush_armed = False
            self._flush_lease_batches()

    def _flush_lease_batches(self):
        from ray_tpu._private.protocol import MsgType

        with self._lease_out_lock:
            batches = {
                cid: results
                for cid, results in self._lease_outbox.items()
                if results
            }
            for cid in batches:
                self._lease_outbox[cid] = []
            stats, self._stats_buffer = self._stats_buffer, []
        for cid, results in batches.items():
            conn = self._lease_conns.get(cid)
            if conn is None or conn.closed:
                continue  # holder gone: its conn-loss path owns recovery
            self.cw.io.spawn(conn.send(MsgType.LEASE_DONE, {"results": results}))
        if stats:
            try:
                self.cw.io.spawn(
                    self.cw.conn.send(
                        MsgType.TASK_STATS,
                        {"node_id": self.cw.node_id, "records": stats},
                    )
                )
            except Exception:  # graftlint: disable=silent-except -- stats are best-effort observability; the completions above are what correctness needs
                pass

    def on_preempt(self, payload: dict) -> dict:
        """Checkpoint request from the head's preemptive scheduler
        (PREEMPT_ACTOR), run on a dedicated thread (core_worker spawns
        it).  Contract: the actor's optional ``__ray_save__`` runs under
        the actor lock (a sequential actor is never checkpointed
        mid-call) within the head's deadline; the returned state is
        serialized into head KV ``actor_ckpt:<actor_id>`` BEFORE we
        reply ok, so the head can SIGKILL this process immediately after
        — ``__ray_restore__`` receives it verbatim on respawn.  Any
        failure (busy past the deadline, save raised, no instance)
        replies not-ok and the head escalates to a budget-charged kill."""
        import time as _time

        inst = self.actor.instance
        if inst is None:
            return {"ok": False, "error": "no actor instance"}
        actor_id = bytes(payload.get("actor_id") or b"")
        deadline = float(payload.get("save_deadline_s") or 5.0)
        save = getattr(inst, "__ray_save__", None)
        start = _time.time()
        if not self.actor_lock.acquire(timeout=deadline):
            return {"ok": False, "error": "actor busy past the save deadline"}
        fenced = False
        try:
            # the lock only fences NEW user code; a call whose method
            # already returned may still be storing results / flushing
            # TASK_DONE — wait it out, or the head would see the task in
            # running_tasks at kill time, requeue it, and double-execute
            # it against checkpointed state that already includes it
            if not self._drain_inflight(start + deadline):
                return {
                    "ok": False,
                    "error": "in-flight call still reporting past the "
                    "save deadline",
                }
            if save is None:
                # nothing to checkpoint: release is still graceful
                # (respawn re-runs __init__ from the original creation
                # args) — hold the fence so no call ACKs a mutation the
                # fresh __init__ then silently discards
                fenced = True
                return {"ok": True, "saved": False}
            state = save()
            if _time.time() - start > deadline:
                # the head's rpc timeout has already escalated (or is
                # about to); don't ship a checkpoint the protocol
                # considers dead
                return {
                    "ok": False,
                    "error": "__ray_save__ exceeded its deadline",
                }
            blob = serialization.dumps(state)
            self.cw.kv_put(f"actor_ckpt:{actor_id.hex()}", blob)
            # fence: the lock stays HELD from here until the head's
            # SIGKILL lands — a queued call running (and ACKing a result
            # to its caller) after the snapshot would be silently rolled
            # back by the restore.  Deliberately never released on the
            # success path; this process is about to die.
            fenced = True
            return {"ok": True, "saved": True}
        finally:
            if not fenced:
                self.actor_lock.release()

    def _drain_inflight(self, deadline_ts: float) -> bool:
        """Wait (bounded) until no call sits between dequeue and its
        TASK_DONE flush.  Caller holds actor_lock, so no NEW call can
        enter user code while we wait; only completion tails drain."""
        import time as _time

        with self._inflight_cv:
            while self._inflight:
                rem = deadline_ts - _time.time()
                if rem <= 0:
                    return False
                self._inflight_cv.wait(rem)
            return True

    def register_with_lease_agent(self, agent_addr: str, direct_port: int):
        """Announce this worker to its node's raylet lease agent
        (raylet/lease_agent.py) so node-affine leases grant locally.  The
        connection doubles as the liveness signal: the agent forgets the
        worker when it drops."""
        from ray_tpu._private.config import RayConfig
        from ray_tpu._private.protocol import Connection, MsgType

        host, port_s = agent_addr.rsplit(":", 1)

        async def _register():
            conn = await Connection.connect(
                host, int(port_s), RayConfig.connect_timeout_s, retry=False
            )
            await conn.send(
                MsgType.REGISTER_WORKER,
                {
                    "worker_id": self.cw.worker_id.binary(),
                    "pid": os.getpid(),
                    "direct_addr": f"0.0.0.0:{direct_port}",
                    "has_tpu": bool(os.environ.get("RAY_TPU_WORKER_TPU")),
                },
            )
            return conn

        try:
            self._agent_conn = self.cw.io.call(_register(), timeout=10)
        except Exception:  # noqa: BLE001 -- local dispatch is an optimization; head grants still work
            traceback.print_exc(file=sys.stderr)
            self._agent_conn = None

    def _notify_agent_dedicated(self):
        """Tell the lease agent this worker now belongs to an actor and
        must never be leased."""
        conn = getattr(self, "_agent_conn", None)
        if conn is None or conn.closed:
            return
        from ray_tpu._private.protocol import MsgType

        try:
            self.cw.io.spawn(
                conn.send(
                    MsgType.REGISTER_WORKER,
                    {"worker_id": self.cw.worker_id.binary(), "dedicated": True},
                )
            )
        except Exception:  # graftlint: disable=silent-except -- best-effort; the agent also learns via lease-push failures
            pass

    def dag_runtime(self):
        """Lazy compiled-DAG runtime (ray_tpu/dag/executor.py) — created on
        the first DAG_SETUP so workers that never join a compiled graph
        never import the dag subsystem.  Only called from the io loop
        (direct-server frame handlers), so no lock is needed."""
        if self._dag_runtime is None:
            from ray_tpu.dag.executor import DagWorkerRuntime

            self._dag_runtime = DagWorkerRuntime(self)
        return self._dag_runtime

    # ------------------------------------------------------------ execution

    def _execute_guarded(self, spec: TaskSpec, reply_to=None):
        with self._inflight_cv:
            self._inflight += 1
        try:
            self._execute_guarded_inner(spec, reply_to)
        finally:
            # retire AFTER the TASK_DONE flush inside the inner call: the
            # reattach announce must cover a completion still in flight
            with self._head_inflight_lock:
                self._head_inflight.pop(bytes(spec.task_id), None)
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def _execute_guarded_inner(self, spec: TaskSpec, reply_to=None):
        import time as _time

        from ray_tpu._private.config import RayConfig

        sealed: List[bytes] = []
        contained: Dict[bytes, List[bytes]] = {}
        inline: Dict[bytes, list] = {}  # oid -> SerializedObject wire (direct replies)
        error: Optional[str] = None
        stored_error = False
        exec_start = _time.time()
        direct = reply_to is not None
        # flight-recorder stamps ride the spec (task_events.py); None when
        # recording is off — each stamp site below is one None check
        ph = spec.phases
        if ph is not None:
            ph["worker_dequeue"] = exec_start
        # nested submissions made by this task inherit its band: without
        # this, a best-effort job's fan-out would silently escalate to the
        # pool worker's default (band 1) and could preempt other tenants
        self.cw.default_priority = spec.priority
        try:
            if spec.task_id in self.cancelled:
                raise RayTaskError(
                    spec.function_name or spec.method_name,
                    "TaskCancelledError: cancelled",
                )
            results = self._execute(spec)
            if ph is not None:
                ph["exec_end"] = ph["put_start"] = _time.time()
            outs = self._normalize_returns(spec, results)
            limit = RayConfig.max_direct_call_object_size
            for oid, value in outs:
                sobj = serialization.serialize(value)
                # direct small refless results reply inline and never touch
                # the store or the head (the reference's in-process memory
                # store for direct-call returns, core_worker.cc:1146);
                # results CONTAINING refs go through the store so the
                # head's containment pinning covers them
                if direct and sobj.total_bytes() <= limit and not sobj.contained:
                    inline[oid] = sobj.to_wire()
                    continue
                # refs to OUR memory-store-only values (results of direct
                # calls we made) must be globally resolvable before they
                # ship inside this return
                self.cw._promote_memory_objects(sobj.contained)
                self.cw.store.put_serialized(oid, sobj)
                sealed.append(oid)
                if sobj.contained:
                    contained[oid] = sobj.contained
            if ph is not None:
                ph["put_end"] = _time.time()
        except BaseException as e:  # noqa: BLE001
            name = spec.function_name or spec.method_name
            # crash forensics: the last-K lines THIS process captured ride
            # inside the error object to the driver and inside the
            # ERROR_REPORT record to the head's dedup ring
            tail = log_plane.recent_tail(RayConfig.error_log_tail_lines)
            if isinstance(e, RayTaskError):
                err = e
                if tail and not err.log_tail:
                    err.log_tail = tail
            else:
                err = RayTaskError.from_exception(name, e, log_tail=tail)
            error = f"{type(e).__name__}: {e}"
            self._report_error(spec, e, err, tail)
            # store the error as the value of every return object
            try:
                for oid in spec.return_object_ids():
                    sobj = serialization.serialize(err)
                    if direct and not sobj.contained:
                        inline[oid] = sobj.to_wire()
                        continue
                    self.cw.store.put_serialized(oid, sobj)
                    sealed.append(oid)
                    if sobj.contained:
                        # refs pickled inside the exception value need the
                        # same containment pin as normal returns
                        contained[oid] = sobj.contained
                stored_error = True
            except BaseException:  # graftlint: disable=silent-except -- reflected in stored_error; the print_exc below logs the original failure
                stored_error = False
            traceback.print_exc(file=sys.stderr)
        finally:
            self.cw.current_task_id = None
            log_plane.clear_task_context()
        if direct:
            lease_mode = reply_to[0] == "lease"
            # over-limit / ref-containing results were stored: seal them at
            # the head first, then answer the caller (inline errors raise
            # client-side on deserialize, like stored ones)
            try:
                if sealed:
                    self.cw.task_done(
                        spec.task_id,
                        sealed,
                        None,
                        True,
                        exec_start=exec_start,
                        exec_end=_time.time(),
                        contained=contained,
                        # lease records ship on the batched TASK_STATS
                        # plane instead (tagged granted_by) — stamping
                        # both would double-count the flight recorder
                        phases=None if lease_mode else ph,
                    )
            except Exception:
                traceback.print_exc(file=sys.stderr)
            # inline-only direct replies skip task_done, but refs this call
            # deserialized into actor state still sit in the batched ADD_REF
            # buffer — declare them before the caller (who holds the only
            # head-visible pin via its arg keepalives) sees the reply and
            # releases, or the late add resurrects a freed count
            self.cw.flush_ref_adds()
            if lease_mode:
                self._queue_lease_result(reply_to[1], spec, inline, sealed, ph)
                return
            conn, rid = reply_to
            self.cw.io.spawn(
                conn.reply(rid, {"inline": inline, "stored": sealed})
            )
            return
        try:
            self.cw.task_done(
                spec.task_id,
                sealed,
                error,
                stored_error,
                exec_start=exec_start,
                exec_end=_time.time(),
                contained=contained,
                phases=ph,
            )
        except Exception:
            traceback.print_exc(file=sys.stderr)
            os._exit(1)  # lost the head: die, the head treats it as worker death

    def _report_error(self, spec: TaskSpec, exc: BaseException, err, tail):
        """Fire-and-forget structured error record to the head's dedup
        ring (ERROR_REPORT — the resurrected ERROR_PUSH role).  Never
        raises: error reporting must not mask the task error itself."""
        try:
            tb = getattr(err, "traceback_str", "") or ""
            name = spec.function_name or spec.method_name
            self.cw.report_error(
                {
                    "signature": _error_signature(exc, name),
                    "kind": "actor_task" if spec.actor_id else "task",
                    "exc_type": type(exc).__name__,
                    "message": str(exc)[:512],
                    "name": name,
                    "traceback": tb[-8192:],
                    "log_tail": tail,
                    "job_id": bytes(spec.job_id).hex() if spec.job_id else "",
                    "task_id": bytes(spec.task_id).hex(),
                    "actor_id": bytes(spec.actor_id).hex() if spec.actor_id else "",
                    "pid": os.getpid(),
                }
            )
        except Exception:  # graftlint: disable=silent-except -- forensics plane is best-effort; the task error itself is already stored
            pass

    def _apply_runtime_env(self, spec: TaskSpec):
        """env_vars / working_dir / py_modules / offline-pip-venv
        materialized in-process before execution (reference:
        _private/runtime_env/ — theirs sets up dedicated workers via the
        agent; see _private/runtime_env.py).  Returns the undo so a
        reused pool worker doesn't leak shipped modules or an activated
        venv into later tasks."""
        from ray_tpu._private.runtime_env import apply_runtime_env

        return apply_runtime_env(
            self.cw,
            spec.runtime_env or {},
            session_dir=os.path.dirname(os.environ.get("RAY_TPU_STORE_PATH", "")),
        )

    def _execute(self, spec: TaskSpec):
        from ray_tpu.util.tracing import span_scope

        self.cw.current_task_id = spec.task_id
        if log_plane.enabled:
            # running-task identity for the structured log plane: every
            # line this task prints is stamped with it (O(1) per line —
            # one dict swap here, one dict merge per line)
            cls = self.actor.cls
            log_plane.task_context(
                task=bytes(spec.task_id).hex(),
                trace=(spec.trace_ctx or {}).get("trace_id") or None,
                job=bytes(spec.job_id).hex() if spec.job_id else None,
                actor=bytes(spec.actor_id).hex() if spec.actor_id else None,
                cls=getattr(cls, "__name__", None) if spec.actor_id else None,
            )
        with span_scope(spec.trace_ctx):
            return self._execute_inner(spec)

    def _execute_inner(self, spec: TaskSpec):
        import time as _time

        # arg-fetch phase covers runtime-env materialization, argument
        # resolution (ref pulls), and the function-table fetch — everything
        # between dequeue and the first line of user code
        ph = spec.phases
        if ph is not None:
            ph["arg_fetch_start"] = _time.time()
        undo_env = self._apply_runtime_env(spec)
        if spec.task_type == NORMAL_TASK:
            # pool workers are reused: the env (sys.path entries, env vars,
            # cwd) must not leak into the next (unrelated) task — even when
            # arg decode or the function fetch fails.  Actors keep theirs:
            # the env belongs to the actor.
            try:
                args, kwargs = self.cw.decode_args(spec.args)
                fn = self.cw.fetch_function(spec.function_id)
                if ph is not None:
                    ph["arg_fetch_end"] = ph["exec_start"] = _time.time()
                return fn(*args, **kwargs)
            finally:
                undo_env()
        args, kwargs = self.cw.decode_args(spec.args)
        if spec.task_type == ACTOR_CREATION_TASK:
            cls = self.cw.fetch_function(spec.function_id)
            self.actor.cls = cls
            self.actor.spec_wire = spec.to_wire()
            if _is_async_actor(cls):
                # async actors process calls concurrently on one event loop
                # (reference: fiber-based async actors, core_worker fiber.h;
                # default max concurrency 1000 for asyncio actors)
                self._start_async_loop()
                concurrency = max(spec.max_concurrency, 100)
            else:
                concurrency = spec.max_concurrency
            if concurrency > 1:
                self.actor.executor = ThreadPoolExecutor(max_workers=concurrency)
                self._concurrency_sem = threading.Semaphore(concurrency)
            if ph is not None:
                ph["arg_fetch_end"] = ph["exec_start"] = _time.time()
            self._notify_agent_dedicated()  # actor workers are never leased
            self.actor.instance = cls(*args, **kwargs)
            if spec.preemptible:
                # respawn-with-restore: a checkpoint saved by a prior
                # incarnation's __ray_save__ hands the state back before
                # any queued call runs (one KV get, preemptible-only cost)
                self._maybe_restore(spec)
            self._start_direct_server(spec.actor_id)
            return None
        if spec.task_type == ACTOR_TASK:
            if ph is not None:
                ph["arg_fetch_end"] = ph["exec_start"] = _time.time()
            inst = self.actor.instance
            if inst is None:
                raise RuntimeError("actor instance not initialized")
            if spec.method_name == "_ray_tpu_init_collective":
                # driver-side create_collective_group() trampoline: join the
                # group in this actor's process (reference analog: declared
                # groups lazily initialized inside each actor,
                # collective.py:151)
                from ray_tpu.util.collective import init_collective_group

                world_size, rank, backend, group_name, *rest = args
                init_collective_group(
                    world_size, rank, backend, group_name,
                    rendezvous_nonce=rest[0] if rest else "",
                )
                return None
            method = getattr(inst, spec.method_name)
            if inspect.iscoroutinefunction(getattr(method, "__func__", method)):
                import asyncio

                fut = asyncio.run_coroutine_threadsafe(method(*args, **kwargs), self.actor.async_loop)
                return fut.result()
            if self._concurrency_sem is None:
                # sequential actor: eager calls and resident compiled-DAG
                # steps (dag/executor.py takes the same lock) stay mutually
                # excluded, preserving the one-call-at-a-time contract.
                # Step OUT of the in-flight count while waiting for the
                # lock: a preemption fence holding it needs to see
                # quiescence, and a call that never entered user code is
                # exactly what the head safely requeues after the kill —
                # counting it would turn every racing benign call into a
                # forced (budget-charged) preemption.
                with self._inflight_cv:
                    self._inflight -= 1
                    self._inflight_cv.notify_all()
                self.actor_lock.acquire()
                try:
                    with self._inflight_cv:
                        self._inflight += 1
                    return method(*args, **kwargs)
                finally:
                    self.actor_lock.release()
            return method(*args, **kwargs)
        raise ValueError(f"unknown task type {spec.task_type}")

    def _maybe_restore(self, spec: TaskSpec):
        key = f"actor_ckpt:{bytes(spec.actor_id).hex()}"
        blob = self.cw.kv_get(key)
        if not blob:
            return
        restore = getattr(self.actor.instance, "__ray_restore__", None)
        if restore is None:
            return
        # a raising restore fails the creation task, which destroys the
        # actor with "creation failed: ..." — a corrupt checkpoint must be
        # loud, not silently discarded
        restore(serialization.loads(bytes(blob)))
        # one-shot: a consumed checkpoint must not survive into a LATER
        # genuine-fault restart, which promises a fresh __init__ — without
        # this, a crash long after re-admission would silently roll the
        # actor back to the stale preemption snapshot
        self.cw.kv_del(key)

    def _normalize_returns(self, spec: TaskSpec, results: Any):
        oids = spec.return_object_ids()
        if spec.num_returns == 1:
            return [(oids[0], results)]
        if results is None:
            results = [None] * spec.num_returns
        results = list(results)
        if len(results) != spec.num_returns:
            raise ValueError(
                f"task declared num_returns={spec.num_returns} but returned {len(results)} values"
            )
        return list(zip(oids, results))

    def _start_async_loop(self):
        import asyncio

        loop = asyncio.new_event_loop()
        self.actor.async_loop = loop
        t = threading.Thread(target=loop.run_forever, name="actor-async", daemon=True)
        t.start()

    def ensure_direct_server(self) -> int:
        """Start (once) this worker's direct-call server and return its
        port — the worker→worker/driver data path that keeps the head out
        of the per-call loop (reference analog: CoreWorker's PushTask gRPC
        service, direct_actor_task_submitter.cc).  Every worker runs one
        now, not just actors: the lease fast path pushes whole task queues
        here (LEASE_PUSH), so the address rides worker registration."""
        import asyncio

        from ray_tpu._private.protocol import Connection, MsgType

        if self._direct_server is not None:
            return self._direct_port

        async def _serve(reader, writer):
            conn = Connection(reader, writer)
            try:
                while True:
                    msg_type, rid, payload = await conn.read_frame()
                    if msg_type == MsgType.ACTOR_CALL:
                        self.task_queue.put(
                            {"spec": payload["spec"], "direct": (conn, rid)}
                        )
                    elif msg_type == MsgType.LEASE_PUSH:
                        # a lease holder's batched task queue: O(1) enqueue
                        # per spec, completions batch back on LEASE_DONE
                        for wire in payload.get("specs", []):
                            self.task_queue.put({"spec": wire, "lease": conn})
                    elif msg_type == MsgType.DAG_PUSH:
                        # compiled-step doorbell: O(1) enqueue to the node's
                        # channel, the resident executor thread does the rest
                        if self._dag_runtime is not None:
                            self._dag_runtime.handle_push(payload)
                    elif msg_type == MsgType.DAG_SETUP:
                        try:
                            reply = await self.dag_runtime().handle_setup(payload, conn)
                        except Exception as e:  # noqa: BLE001 -- reported to the compiling driver
                            await conn.reply(rid, {}, error=f"{type(e).__name__}: {e}")
                        else:
                            await conn.reply(rid, reply)
                    elif msg_type == MsgType.DAG_ARM:
                        # gang-setup phase 2: start resident loops installed
                        # by an unarmed DAG_SETUP (atomic multi-host arming)
                        if self._dag_runtime is None:
                            await conn.reply(
                                rid, {}, error="no dag runtime (setup never ran)"
                            )
                        else:
                            try:
                                reply = await self._dag_runtime.handle_arm(payload)
                            except Exception as e:  # noqa: BLE001 -- reported to the compiling driver
                                await conn.reply(
                                    rid, {}, error=f"{type(e).__name__}: {e}"
                                )
                            else:
                                await conn.reply(rid, reply)
                    elif msg_type == MsgType.DAG_TEARDOWN:
                        if self._dag_runtime is None:
                            await conn.reply(rid, {"ok": True, "absent": True})
                        else:
                            await conn.reply(
                                rid, await self._dag_runtime.handle_teardown(payload)
                            )
                    elif msg_type == MsgType.ENGINE_STREAM:
                        # serve-engine token-stream negotiation: attach a
                        # dag channel to a live stream / cancel one.  The
                        # frames themselves then ride DAG_PUSH above.
                        try:
                            from ray_tpu.serve.engine import (
                                transport as engine_transport,
                            )

                            reply = await engine_transport.handle_frame(payload, conn)
                        except Exception as e:  # noqa: BLE001 -- reported to the attaching consumer
                            await conn.reply(
                                rid, {}, error=f"{type(e).__name__}: {e}"
                            )
                        else:
                            await conn.reply(rid, reply)
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                pass
            finally:
                # a dag dies with its driver conn: stop executors, release
                # channels, return to eager-only service
                if self._dag_runtime is not None:
                    self._dag_runtime.on_conn_lost(conn)
                # engine token streams die with their consumer conn too
                # (writer + shm ring reclaimed there); sys.modules guard so
                # workers that never streamed don't import the serve engine
                eng_transport = sys.modules.get("ray_tpu.serve.engine.transport")
                if eng_transport is not None:
                    eng_transport.conn_lost(conn)

        async def _start():
            server = await asyncio.start_server(_serve, "0.0.0.0", 0)
            port = server.sockets[0].getsockname()[1]
            self._direct_server = server
            return port

        try:
            self._direct_port = self.cw.io.call(_start(), timeout=10)
        except Exception:
            traceback.print_exc(file=sys.stderr)  # head path keeps working
            self._direct_port = 0
        return self._direct_port

    def _start_direct_server(self, actor_id: bytes):
        """Announce this actor's direct-call endpoint to the head (the
        server itself is the shared per-worker one)."""
        from ray_tpu._private.config import RayConfig
        from ray_tpu._private.protocol import MsgType

        if not RayConfig.enable_direct_actor_calls:
            return
        port = self.ensure_direct_server()
        if not port:
            return
        try:
            self.cw.request(
                MsgType.ACTOR_STATE,
                {"actor_id": actor_id, "direct_addr": f"0.0.0.0:{port}"},
            )
        except Exception:
            traceback.print_exc(file=sys.stderr)  # head path keeps working


def _is_async_actor(cls) -> bool:
    return any(
        inspect.iscoroutinefunction(m)
        for _, m in inspect.getmembers(cls, predicate=inspect.isfunction)
    )


def _error_signature(exc: BaseException, name: str) -> str:
    """Dedup key for the head's error ring: exception type + function +
    deepest in-user-code frame.  Two crashes from the same broken line
    collapse into one signature however many workers hit it."""
    file, line = "", 0
    tb = exc.__traceback__
    while tb is not None:
        file = os.path.basename(tb.tb_frame.f_code.co_filename)
        line = tb.tb_lineno
        tb = tb.tb_next
    return f"{type(exc).__name__}:{name}:{file}:{line}"


def _own_log_file() -> str:
    """Where this process's stdout actually lands (the worker log the
    raylet/zygote/head wired us to) — registered with the head so
    LOG_FETCH can address this worker's output by entity."""
    try:
        path = os.readlink("/proc/self/fd/1")
        return path if path.startswith("/") else ""
    except OSError:
        return ""


def main():
    # stack dumps on demand: `kill -USR1 <worker pid>` writes every
    # thread's traceback to the worker log — the first tool for "which
    # worker is wedged, and where" at fleet scale.  Shared helper: head,
    # raylet, and dashboard mains register the same dump.
    from ray_tpu._private.profiler import install_sigusr1

    install_sigusr1()

    host, port = os.environ["RAY_TPU_HEAD"].split(":")
    node_id = bytes.fromhex(os.environ["RAY_TPU_NODE_ID"])
    from ray_tpu._private.config import RayConfig

    if os.environ.get("RAY_TPU_SYSTEM_CONFIG"):
        RayConfig.initialize_from_json(os.environ["RAY_TPU_SYSTEM_CONFIG"])

    # structured log capture FIRST, so even registration-path output is
    # stamped.  Covers exec-spawned workers and zygote-forked children
    # alike — both re-enter main() with fd 1/2 already dup2'd onto the
    # worker log (RAY_TPU_LOG_STRUCTURED=0 keeps raw lines; install is a
    # no-op then).
    log_plane.install(node=node_id.hex()[:8])

    from ray_tpu.core.core_worker import CoreWorker

    cw = CoreWorker(host, int(port), mode="worker")
    log_plane.set_static(wid=cw.worker_id.hex()[:8])
    runtime = WorkerRuntime(cw)
    # handler must be live BEFORE registering: the head pushes the first task
    # the moment registration lands
    cw.set_push_task_handler(runtime.on_push)
    cw.set_preempt_handler(runtime.on_preempt)
    cw.set_reattach_state_provider(runtime.reattach_state)
    # every worker serves direct calls now (lease pushes + actor calls);
    # the address rides registration so the head can grant leases on it
    direct_port = 0
    if RayConfig.enable_direct_actor_calls or RayConfig.lease_cache_enabled:
        direct_port = runtime.ensure_direct_server()
    cw.register_as_worker(
        node_id,
        os.getpid(),
        has_tpu=bool(os.environ.get("RAY_TPU_WORKER_TPU")),
        direct_addr=f"0.0.0.0:{direct_port}" if direct_port else "",
        log_file=_own_log_file(),
    )
    # node-local dispatch: announce to this node's raylet lease agent (if
    # any) so node-affine leases grant without a head round-trip
    agent_addr = os.environ.get("RAY_TPU_RAYLET_DISPATCH", "")
    if agent_addr and direct_port:
        runtime.register_with_lease_agent(agent_addr, direct_port)

    # mark this process as a connected worker for nested API calls
    from ray_tpu._private import worker as worker_mod

    worker_mod.global_worker.core_worker = cw
    worker_mod.global_worker.mode = "worker"

    # a worker whose head died must exit, not linger as an orphan blocked
    # on its task queue (reference: workers die with their raylet); the
    # sentinel unblocks run(), and hard-exit below skips joining actor
    # executor threads that may be wedged in user code
    cw.on_disconnect(lambda: runtime.task_queue.put(None))
    runtime.run()
    os._exit(0)


if __name__ == "__main__":
    main()
