"""Worker process main: execute tasks pushed by the head.

Analog of the reference's default_worker.py + the C++ task execution loop
(reference: python/ray/_private/workers/default_worker.py,
src/ray/core_worker/core_worker.cc RunTaskExecutionLoop:2176 /
ExecuteTask:2231, and the Cython execute_task upcall _raylet.pyx:596).

A worker is either a pool worker (runs one normal task at a time) or an
actor-dedicated worker (holds the instance; executes its method calls in
submission order, or concurrently up to max_concurrency, or on an asyncio
loop for async actors — the analog of reference concurrency groups /
fiber-based async actors, src/ray/core_worker/transport/
concurrency_group_manager.cc + fiber.h).
"""

from __future__ import annotations

import inspect
import os
import queue
import sys
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu._private import serialization
from ray_tpu._private.ids import JobID
from ray_tpu._private.task_spec import ACTOR_CREATION_TASK, ACTOR_TASK, NORMAL_TASK, TaskSpec
from ray_tpu.exceptions import RayTaskError


class _ActorState:
    def __init__(self):
        self.instance: Any = None
        self.cls: Any = None
        self.async_loop: Optional[Any] = None  # asyncio loop for async actors
        self.executor: Optional[ThreadPoolExecutor] = None


class WorkerRuntime:
    def __init__(self, core_worker):
        self.cw = core_worker
        self.actor = _ActorState()
        self.task_queue: "queue.Queue[dict]" = queue.Queue()
        self.cancelled: set = set()
        self._concurrency_sem: Optional[threading.Semaphore] = None

    # ------------------------------------------------------------ main loop

    def run(self):
        """Pull pushed tasks off the queue and execute (the analog of
        RunTaskExecutionLoop)."""
        while True:
            payload = self.task_queue.get()
            if payload is None:
                break
            if "cancel" in payload:
                self.cancelled.add(payload["cancel"])
                continue
            spec = TaskSpec.from_wire(payload["spec"])
            if spec.task_type == ACTOR_TASK and self._concurrency_sem is not None:
                # concurrent actor: run in the pool, keep pulling
                self.actor.executor.submit(self._execute_guarded, spec)
            else:
                self._execute_guarded(spec)

    def on_push(self, payload: dict):
        """Called from the io thread; never block it."""
        if payload.get("directive"):
            return  # spawn directives are raylet business, not ours
        self.task_queue.put(payload)

    # ------------------------------------------------------------ execution

    def _execute_guarded(self, spec: TaskSpec):
        import time as _time

        sealed: List[bytes] = []
        contained: Dict[bytes, List[bytes]] = {}
        error: Optional[str] = None
        stored_error = False
        exec_start = _time.time()
        try:
            if spec.task_id in self.cancelled:
                raise RayTaskError(
                    spec.function_name or spec.method_name,
                    "TaskCancelledError: cancelled",
                )
            results = self._execute(spec)
            outs = self._normalize_returns(spec, results)
            for oid, value in outs:
                sobj = serialization.serialize(value)
                self.cw.store.put_serialized(oid, sobj)
                sealed.append(oid)
                if sobj.contained:
                    contained[oid] = sobj.contained
        except BaseException as e:  # noqa: BLE001
            name = spec.function_name or spec.method_name
            if isinstance(e, RayTaskError):
                err = e
            else:
                err = RayTaskError.from_exception(name, e)
            error = f"{type(e).__name__}: {e}"
            # store the error as the value of every return object
            try:
                for oid in spec.return_object_ids():
                    sobj = serialization.serialize(err)
                    self.cw.store.put_serialized(oid, sobj)
                    sealed.append(oid)
                    if sobj.contained:
                        # refs pickled inside the exception value need the
                        # same containment pin as normal returns
                        contained[oid] = sobj.contained
                stored_error = True
            except BaseException:
                stored_error = False
            traceback.print_exc(file=sys.stderr)
        finally:
            self.cw.current_task_id = None
        try:
            self.cw.task_done(
                spec.task_id,
                sealed,
                error,
                stored_error,
                exec_start=exec_start,
                exec_end=_time.time(),
                contained=contained,
            )
        except Exception:
            traceback.print_exc(file=sys.stderr)
            os._exit(1)  # lost the head: die, the head treats it as worker death

    def _apply_runtime_env(self, spec: TaskSpec):
        """env_vars + working_dir (reference: _private/runtime_env/ —
        theirs sets up dedicated workers via the agent; here the worker
        applies the env in-process before execution; conda/pip isolation
        is out of scope on a fixed TPU-VM image and raises)."""
        renv = spec.runtime_env or {}
        unsupported = set(renv) - {"env_vars", "working_dir"}
        if unsupported:
            raise ValueError(f"unsupported runtime_env keys: {sorted(unsupported)}")
        for k, v in (renv.get("env_vars") or {}).items():
            os.environ[str(k)] = str(v)
        wd = renv.get("working_dir")
        if wd:
            os.chdir(wd)
            if wd not in sys.path:
                sys.path.insert(0, wd)

    def _execute(self, spec: TaskSpec):
        self.cw.current_task_id = spec.task_id
        self._apply_runtime_env(spec)
        args, kwargs = self.cw.decode_args(spec.args)
        if spec.task_type == NORMAL_TASK:
            fn = self.cw.fetch_function(spec.function_id)
            return fn(*args, **kwargs)
        if spec.task_type == ACTOR_CREATION_TASK:
            cls = self.cw.fetch_function(spec.function_id)
            self.actor.cls = cls
            if _is_async_actor(cls):
                # async actors process calls concurrently on one event loop
                # (reference: fiber-based async actors, core_worker fiber.h;
                # default max concurrency 1000 for asyncio actors)
                self._start_async_loop()
                concurrency = max(spec.max_concurrency, 100)
            else:
                concurrency = spec.max_concurrency
            if concurrency > 1:
                self.actor.executor = ThreadPoolExecutor(max_workers=concurrency)
                self._concurrency_sem = threading.Semaphore(concurrency)
            self.actor.instance = cls(*args, **kwargs)
            return None
        if spec.task_type == ACTOR_TASK:
            inst = self.actor.instance
            if inst is None:
                raise RuntimeError("actor instance not initialized")
            if spec.method_name == "_ray_tpu_init_collective":
                # driver-side create_collective_group() trampoline: join the
                # group in this actor's process (reference analog: declared
                # groups lazily initialized inside each actor,
                # collective.py:151)
                from ray_tpu.util.collective import init_collective_group

                world_size, rank, backend, group_name = args
                init_collective_group(world_size, rank, backend, group_name)
                return None
            method = getattr(inst, spec.method_name)
            if inspect.iscoroutinefunction(getattr(method, "__func__", method)):
                import asyncio

                fut = asyncio.run_coroutine_threadsafe(method(*args, **kwargs), self.actor.async_loop)
                return fut.result()
            return method(*args, **kwargs)
        raise ValueError(f"unknown task type {spec.task_type}")

    def _normalize_returns(self, spec: TaskSpec, results: Any):
        oids = spec.return_object_ids()
        if spec.num_returns == 1:
            return [(oids[0], results)]
        if results is None:
            results = [None] * spec.num_returns
        results = list(results)
        if len(results) != spec.num_returns:
            raise ValueError(
                f"task declared num_returns={spec.num_returns} but returned {len(results)} values"
            )
        return list(zip(oids, results))

    def _start_async_loop(self):
        import asyncio

        loop = asyncio.new_event_loop()
        self.actor.async_loop = loop
        t = threading.Thread(target=loop.run_forever, name="actor-async", daemon=True)
        t.start()


def _is_async_actor(cls) -> bool:
    return any(
        inspect.iscoroutinefunction(m)
        for _, m in inspect.getmembers(cls, predicate=inspect.isfunction)
    )


def main():
    host, port = os.environ["RAY_TPU_HEAD"].split(":")
    node_id = bytes.fromhex(os.environ["RAY_TPU_NODE_ID"])
    from ray_tpu._private.config import RayConfig

    if os.environ.get("RAY_TPU_SYSTEM_CONFIG"):
        RayConfig.initialize_from_json(os.environ["RAY_TPU_SYSTEM_CONFIG"])

    from ray_tpu.core.core_worker import CoreWorker

    cw = CoreWorker(host, int(port), mode="worker")
    runtime = WorkerRuntime(cw)
    # handler must be live BEFORE registering: the head pushes the first task
    # the moment registration lands
    cw.set_push_task_handler(runtime.on_push)
    cw.register_as_worker(
        node_id, os.getpid(), has_tpu=bool(os.environ.get("RAY_TPU_WORKER_TPU"))
    )

    # mark this process as a connected worker for nested API calls
    from ray_tpu._private import worker as worker_mod

    worker_mod.global_worker.core_worker = cw
    worker_mod.global_worker.mode = "worker"

    runtime.run()


if __name__ == "__main__":
    main()
