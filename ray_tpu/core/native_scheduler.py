"""ctypes wrapper for the native scheduling core (src/scheduler/scheduler.cc).

Resource names are interned to dense indices here; values cross the
boundary as int64 fixed-point at 1e4 scale (reference:
src/ray/raylet/scheduling/fixed_point.h uses the same factor).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Dict, Optional

from ray_tpu._private.build_native import ensure_lib
from ray_tpu.util.lockwitness import named_lock

SCALE = 10_000
MAX_RESOURCES = 128


class _Lib:
    _instance = None

    @classmethod
    def get(cls):
        if cls._instance is None:
            lib = ctypes.CDLL(ensure_lib("scheduler"))
            lib.sched_create.restype = ctypes.c_void_p
            lib.sched_destroy.argtypes = [ctypes.c_void_p]
            I64P = ctypes.POINTER(ctypes.c_int64)
            lib.sched_upsert_node.restype = ctypes.c_int
            lib.sched_upsert_node.argtypes = [ctypes.c_void_p, ctypes.c_int, I64P, ctypes.c_int]
            lib.sched_remove_node.restype = ctypes.c_int
            lib.sched_remove_node.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.sched_acquire.restype = ctypes.c_int
            lib.sched_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int, I64P, ctypes.c_int]
            lib.sched_acquire_force.argtypes = [ctypes.c_void_p, ctypes.c_int, I64P, ctypes.c_int]
            lib.sched_release.argtypes = [ctypes.c_void_p, ctypes.c_int, I64P, ctypes.c_int]
            lib.sched_utilization.restype = ctypes.c_int64
            lib.sched_utilization.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.sched_available.argtypes = [ctypes.c_void_p, ctypes.c_int, I64P, ctypes.c_int]
            lib.sched_pick_and_acquire.restype = ctypes.c_int
            lib.sched_pick_and_acquire.argtypes = [
                ctypes.c_void_p,
                I64P,
                ctypes.c_int,
                ctypes.c_int64,
                ctypes.c_int,
            ]
            lib.sched_feasible.restype = ctypes.c_int
            lib.sched_feasible.argtypes = [ctypes.c_void_p, I64P, ctypes.c_int]
            cls._instance = lib
        return cls._instance


class NativeScheduler:
    """One per head server: the cluster resource view + hybrid policy."""

    def __init__(self):
        self._lib = _Lib.get()
        self._h = self._lib.sched_create()
        self._names: Dict[str, int] = {}
        self._node_ids: Dict[bytes, int] = {}
        self._idx_to_node: Dict[int, bytes] = {}
        self._next_node = 0
        self._lock = named_lock("NativeScheduler._lock")

    def _intern(self, name: str) -> int:
        idx = self._names.get(name)
        if idx is None:
            if len(self._names) >= MAX_RESOURCES:
                raise ValueError("too many distinct resource types")
            idx = len(self._names)
            self._names[name] = idx
        return idx

    def _vec(self, resources: Dict[str, float]):
        arr = (ctypes.c_int64 * MAX_RESOURCES)()
        top = 0
        for name, value in resources.items():
            i = self._intern(name)
            arr[i] = int(round(value * SCALE))
            top = max(top, i + 1)
        return arr, max(top, len(self._names))

    def _node_idx(self, node_id: bytes, create: bool = False) -> Optional[int]:
        idx = self._node_ids.get(node_id)
        if idx is None and create:
            idx = self._next_node
            self._next_node += 1
            self._node_ids[node_id] = idx
            self._idx_to_node[idx] = node_id
        return idx

    # ----------------------------------------------------------------- api

    def upsert_node(self, node_id: bytes, totals: Dict[str, float]):
        with self._lock:
            idx = self._node_idx(node_id, create=True)
            arr, n = self._vec(totals)
            self._lib.sched_upsert_node(self._h, idx, arr, n)

    def remove_node(self, node_id: bytes):
        with self._lock:
            idx = self._node_idx(node_id)
            if idx is not None:
                self._lib.sched_remove_node(self._h, idx)

    def acquire(self, node_id: bytes, demand: Dict[str, float], force: bool = False) -> bool:
        with self._lock:
            idx = self._node_idx(node_id)
            if idx is None:
                return False
            arr, n = self._vec(demand)
            if force:
                self._lib.sched_acquire_force(self._h, idx, arr, n)
                return True
            return self._lib.sched_acquire(self._h, idx, arr, n) == 0

    def release(self, node_id: bytes, demand: Dict[str, float]):
        with self._lock:
            idx = self._node_idx(node_id)
            if idx is not None:
                arr, n = self._vec(demand)
                self._lib.sched_release(self._h, idx, arr, n)

    def utilization(self, node_id: bytes) -> float:
        with self._lock:
            idx = self._node_idx(node_id)
            if idx is None:
                return 0.0
            return self._lib.sched_utilization(self._h, idx) / SCALE

    def available(self, node_id: bytes) -> Dict[str, float]:
        with self._lock:
            idx = self._node_idx(node_id)
            if idx is None:
                return {}
            arr = (ctypes.c_int64 * MAX_RESOURCES)()
            self._lib.sched_available(self._h, idx, arr, len(self._names))
            return {name: arr[i] / SCALE for name, i in self._names.items()}

    def pick_and_acquire(
        self,
        demand: Dict[str, float],
        spread_threshold: float,
        prefer: Optional[bytes] = None,
    ) -> Optional[bytes]:
        """Hybrid policy decision + reservation in one native call."""
        with self._lock:
            arr, n = self._vec(demand)
            prefer_idx = self._node_ids.get(prefer, -1) if prefer else -1
            idx = self._lib.sched_pick_and_acquire(
                self._h, arr, n, int(spread_threshold * SCALE), prefer_idx
            )
            if idx < 0:
                return None
            return self._idx_to_node[idx]

    def feasible(self, demand: Dict[str, float]) -> bool:
        with self._lock:
            arr, n = self._vec(demand)
            return bool(self._lib.sched_feasible(self._h, arr, n))

    def __del__(self):
        try:
            self._lib.sched_destroy(self._h)
        except Exception:  # graftlint: disable=silent-except -- interpreter-teardown __del__; the lib may already be unloaded
            pass
