"""Device-resident object tier: pin arrays in place, move them over the
collective transfer plane (core/DEVICE_TIER.md; ROADMAP item 3).

The host object plane round-trips every ``put`` of a device array through
device→host→shm (+TCP per hop on a cross-node get).  This module keeps the
array where it already lives — HBM on TPU, device/host memory on the CPU
backend — and records only METADATA at the head: dtype, shape, nbytes, and
which processes hold a live copy (the Pathways discipline, PAPERS.md §2:
accelerator-resident data, host off the transfer-critical path, layered
onto the Ray object-store model, PAPERS.md §1).

Three layers:

- ``DeviceStore``: per-process registry oid → live array.  Same-process
  ``get`` returns the LITERAL object (zero-copy identity, nothing through
  shm).  Capacity-bounded: LRU entries hand off to shm as a META_DEVICE
  envelope (serialization.py) via the ``spill_fn`` the core worker wires,
  after which the ordinary shm→disk spill chain applies — the eviction
  ladder is device → shm → disk, and a later get restores transparently.
- ``DeviceTransferServer``: a plain-thread blocking-socket listener that
  serves token-authenticated typed-array pulls straight from the pinned
  buffer — dcn_backend framing (``send_array_frame``: fixed struct header,
  never pickle), pipelined chunked sends, SO_SNDBUF/SO_RCVBUF sized
  (``_configure_socket``).  Deliberately NOT on the io event loop: a 90MB
  send must never stall heartbeats (graftsan GS001/GS002 contract).
- ``pull_device_object``: the consumer half — one recv_into a
  preallocated buffer; the returned array wraps it (one copy end to end,
  vs ~5 full-payload copies on the host shm+chunk-TCP path).

The head never proxies payload bytes: it directs a consumer at a named
holder (addr + token), caps concurrent pulls per holder
(``device_pull_fanout``) and registers each consumer's cached copy as a
new holder — concurrent broadcast consumers therefore drain as a binomial
tree growing one level per completed pull, the same fan-out shape as
``DcnGroup._broadcast_tree``.
"""

from __future__ import annotations

import logging
import secrets
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private.config import RayConfig
from ray_tpu.util.collective.dcn_backend import (
    _configure_socket,
    _recv_bounded_msg,
    _self_ip,
    _send_msg,
    recv_array_frame,
    send_array_frame,
)
from ray_tpu.util.lockwitness import named_lock

logger = logging.getLogger(__name__)

_HELLO_MAX = 4096


class DevicePullError(ConnectionError):
    """A collective pull from a named device holder failed (holder died,
    evicted the entry, or the wire broke).  The caller reports the failed
    address back to the head, which prunes the holder and falls back to a
    surviving location / the shm envelope / lineage."""


def classify_device_value(value) -> Optional[Tuple[str, int]]:
    """(kind, nbytes) when `value` is a device-tier-able array: a
    top-level jax.Array ("jax") or np.ndarray ("np" — on the CPU backend a
    host array IS the device-resident buffer).  None for everything else:
    containers keep the host pickle path (blast-radius control — refs stay
    ordinary ObjectRefs either way)."""
    import sys

    if isinstance(value, np.ndarray):
        return ("np", int(value.nbytes))
    if "jax" in sys.modules:
        import jax

        if isinstance(value, jax.Array):
            try:
                nbytes = int(value.size) * int(value.dtype.itemsize)
            except Exception:  # graftlint: disable=silent-except -- exotic dtypes (e.g. key arrays) fall back to the host path
                return None
            return ("jax", nbytes)
    return None


class _Entry:
    __slots__ = ("value", "kind", "dtype_str", "shape", "nbytes", "pins", "last_used")

    def __init__(self, value, kind: str, dtype_str: str, shape: tuple, nbytes: int):
        self.value = value
        self.kind = kind
        self.dtype_str = dtype_str
        self.shape = shape
        self.nbytes = nbytes
        self.pins = 0  # transfer serves in flight; pinned entries never evict
        self.last_used = time.monotonic()


class DeviceStore:
    """Per-process device-object registry with LRU handoff to shm."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = named_lock("DeviceStore._lock")
        self._entries: Dict[bytes, _Entry] = {}
        self._bytes = 0
        self.capacity = int(
            capacity if capacity is not None else RayConfig.device_store_capacity
        )
        # wired by the core worker: (oid, entry) -> bool; serializes the
        # entry into shm (META_DEVICE envelope) + re-seals at the head so
        # the tier tag flips device→shm before the device copy drops
        self.spill_fn: Optional[Callable[[bytes, "_Entry"], bool]] = None
        self.evictions = 0

    def put(self, oid: bytes, value, kind: str) -> dict:
        """Register a live array; returns its wire meta.  May evict LRU
        entries through spill_fn to stay under capacity (never the entry
        being inserted)."""
        arr_like = value
        dtype_str = np.dtype(arr_like.dtype).str
        shape = tuple(int(s) for s in arr_like.shape)
        nbytes = (
            int(value.nbytes)
            if kind == "np"
            else int(value.size) * int(value.dtype.itemsize)
        )
        with self._lock:
            if oid in self._entries:
                return self._meta_locked(self._entries[oid])
            entry = _Entry(value, kind, dtype_str, shape, nbytes)
            self._entries[oid] = entry
            self._bytes += nbytes
            victims = self._pick_victims_locked(exclude=oid)
        for vid, ventry in victims:
            self._spill_out(vid, ventry)
        return {
            "kind": kind,
            "dtype": dtype_str,
            "shape": list(shape),
            "nbytes": nbytes,
        }

    def _meta_locked(self, e: _Entry) -> dict:
        return {
            "kind": e.kind,
            "dtype": e.dtype_str,
            "shape": list(e.shape),
            "nbytes": e.nbytes,
        }

    def _pick_victims_locked(self, exclude: bytes) -> List[Tuple[bytes, _Entry]]:
        if self._bytes <= self.capacity:
            return []
        victims = []
        for vid, e in sorted(self._entries.items(), key=lambda kv: kv[1].last_used):
            if self._bytes <= self.capacity:
                break
            if vid == exclude or e.pins > 0:
                continue
            victims.append((vid, e))
            self._bytes -= e.nbytes
            del self._entries[vid]
        return victims

    def _spill_out(self, oid: bytes, entry: _Entry):
        self.evictions += 1
        fn = self.spill_fn
        if fn is None:
            logger.warning(
                "device store over capacity with no spill_fn; dropping %s "
                "(%d bytes) — a later get needs lineage",
                oid.hex()[:16],
                entry.nbytes,
            )
            return
        try:
            fn(oid, entry)
        except Exception:  # noqa: BLE001
            logger.exception(
                "device→shm spill of %s failed; the device copy is gone",
                oid.hex()[:16],
            )

    def get(self, oid: bytes):
        """The literal stored array, or None.  Zero-copy by definition —
        no serialization, no shm, no socket."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return None
            e.last_used = time.monotonic()
            return e.value

    def contains(self, oid: bytes) -> bool:
        with self._lock:
            return oid in self._entries

    def pin_for_serve(self, oid: bytes) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                return None
            e.pins += 1
            e.last_used = time.monotonic()
            return e

    def unpin(self, oid: bytes):
        with self._lock:
            e = self._entries.get(oid)
            if e is not None and e.pins > 0:
                e.pins -= 1

    def delete(self, oid: bytes) -> bool:
        with self._lock:
            e = self._entries.pop(oid, None)
            if e is None:
                return False
            self._bytes -= e.nbytes
            return True

    def stats(self) -> dict:
        with self._lock:
            return {
                "objects": len(self._entries),
                "bytes": self._bytes,
                "capacity": self.capacity,
                "evictions": self.evictions,
            }


def host_image(entry: _Entry) -> memoryview:
    """Contiguous byte view of an entry's host image.  np entries export
    their buffer directly (zero-copy for contiguous arrays); jax entries
    pull to host once — on the CPU backend np.asarray is itself zero-copy
    for an unsharded array."""
    if entry.kind == "np":
        arr = np.ascontiguousarray(entry.value)
    else:
        arr = np.ascontiguousarray(np.asarray(entry.value))
    return memoryview(arr).cast("B")


class DeviceTransferServer:
    """Serves token-authenticated device-object pulls from this process.

    One standing listener thread + one short-lived thread per admitted
    pull (the head's ``device_pull_fanout`` bounds concurrency cluster-
    wide; the local hard cap is a backstop against a misbehaving peer).
    Hello frame (never unpickled): ``devpull\\n<token>\\n<oid hex>``; reply
    ``ok`` + one typed-array frame, or ``err:<reason>``.
    """

    _MAX_SERVE_THREADS = 16

    def __init__(self, store: DeviceStore):
        self.store = store
        self.token = secrets.token_hex(16)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(8)
        port = self._listener.getsockname()[1]
        import os

        host = os.environ.get("RAY_TPU_NODE_IP") or _self_ip()
        self.addr = f"{host}:{port}"
        self._closed = False
        self._serving = threading.Semaphore(self._MAX_SERVE_THREADS)
        self._thread = threading.Thread(
            target=self._accept_loop, name="device-transfer", daemon=True
        )
        self._thread.start()

    def _accept_loop(self):
        self._listener.settimeout(1.0)
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if not self._serving.acquire(blocking=False):
                sock.close()  # over the local backstop; the peer retries
                continue
            threading.Thread(
                target=self._serve_one, args=(sock,), daemon=True
            ).start()

    def _serve_one(self, sock: socket.socket):
        try:
            _configure_socket(sock)
            sock.settimeout(10)
            parts = _recv_bounded_msg(sock, max_len=_HELLO_MAX).decode().split("\n")
            if len(parts) != 3 or parts[0] != "devpull" or parts[1] != self.token:
                sock.close()
                return
            oid = bytes.fromhex(parts[2])
            entry = self.store.pin_for_serve(oid)
            if entry is None:
                _send_msg(sock, b"err:gone")
                sock.close()
                return
            try:
                view = host_image(entry)
                sock.settimeout(600)
                _send_msg(sock, b"ok")
                send_array_frame(sock, entry.dtype_str, entry.shape, view)
            finally:
                self.store.unpin(oid)
            sock.close()
        except Exception:  # graftlint: disable=silent-except -- per-pull serve thread; a broken peer socket is the PULLER's error to surface (it retries against the head)
            try:
                sock.close()
            except OSError:
                pass
        finally:
            self._serving.release()

    def close(self):
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass


def pull_device_object(
    addr: str, token: str, oid: bytes, timeout: float = 300.0
) -> np.ndarray:
    """Pull one device object from a named holder.  Raises DevicePullError
    on any transport/auth/absence failure — the caller's cue to report
    ``device_failed`` to the head and be redirected."""
    host, port = addr.rsplit(":", 1)
    try:
        sock = socket.create_connection((host, int(port)), timeout=10)
    except OSError as e:
        raise DevicePullError(f"dial {addr}: {e}") from e
    try:
        _configure_socket(sock)
        sock.settimeout(timeout)
        _send_msg(sock, f"devpull\n{token}\n{oid.hex()}".encode())
        status = _recv_bounded_msg(sock, max_len=_HELLO_MAX)
        if status != b"ok":
            raise DevicePullError(
                f"holder {addr} refused pull of {oid.hex()[:16]}: "
                f"{status.decode(errors='replace')}"
            )
        return recv_array_frame(sock)
    except DevicePullError:
        raise
    except (OSError, ConnectionError, TimeoutError) as e:
        raise DevicePullError(f"pull from {addr} failed: {e}") from e
    finally:
        try:
            sock.close()
        except OSError:
            pass
